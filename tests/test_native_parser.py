"""Native C++ bulk parser vs the python per-line parsers: bit-parity on
the columnar result, malformed-line handling, and the dataset fast path."""

import numpy as np
import pytest

from paddlebox_tpu.config import flags_scope
from paddlebox_tpu.data import DataFeedDesc, DatasetFactory, SlotDef
from paddlebox_tpu.data.columnar import ColumnarRecords
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.data.parser import CriteoParser, SlotTextParser
from paddlebox_tpu.native import load_native

requires_native = pytest.mark.skipif(load_native() is None,
                                     reason="native lib unavailable")


def _columnar_from_python(parser, path, dense_dim):
    recs = []
    with open(path) as fh:
        for line in fh:
            r = parser.parse(line)
            if r is not None:
                recs.append(r)
    return ColumnarRecords.from_records(recs, dense_dim)


@requires_native
def test_criteo_native_matches_python(tmp_path):
    files = generate_criteo_files(str(tmp_path), num_files=1,
                                  rows_per_file=500, vocab_per_slot=100,
                                  seed=3)
    desc = DataFeedDesc.criteo(batch_size=64)
    p = CriteoParser(desc)
    got = p.parse_file_columnar(files[0])
    assert got is not None
    ref = _columnar_from_python(p, files[0], desc.dense_dim)
    np.testing.assert_array_equal(got["keys"], ref.keys)
    np.testing.assert_array_equal(got["key_slot"], ref.key_slot)
    np.testing.assert_array_equal(got["offsets"], ref.offsets)
    np.testing.assert_allclose(got["dense"], ref.dense, rtol=1e-6)
    np.testing.assert_array_equal(got["label"], ref.label)
    np.testing.assert_array_equal(got["clk"], ref.clk)


@requires_native
def test_criteo_native_skips_malformed(tmp_path):
    good = "1\t" + "\t".join(str(i) for i in range(1, 14)) + "\t" + \
        "\t".join(f"{i:x}" for i in range(26))
    lines = ["garbage line", good, "too\tfew\tfields", good + "\n"]
    f = tmp_path / "bad.txt"
    f.write_text("\n".join(lines))
    desc = DataFeedDesc.criteo(batch_size=4)
    got = CriteoParser(desc).parse_file_columnar(str(f))
    assert len(got["label"]) == 2
    assert (got["label"] == 1.0).all()


@requires_native
def test_slot_text_native_matches_python(tmp_path):
    rng = np.random.default_rng(5)
    slots = [SlotDef("label", "float", 1), SlotDef("dense", "float", 3),
             SlotDef("s1", "uint64"), SlotDef("s2", "uint64"),
             SlotDef("unused", "uint64", is_used=False)]
    desc = DataFeedDesc(slots=slots, batch_size=16, label_slot="label")
    lines = []
    for i in range(200):
        n1 = int(rng.integers(0, 4))
        n2 = int(rng.integers(1, 3))
        parts = ["1", str(int(rng.integers(0, 2)))]
        parts += ["3"] + [f"{rng.normal():.4f}" for _ in range(3)]
        parts += [str(n1)] + [str(int(rng.integers(0, 10**12)))
                              for _ in range(n1)]
        parts += [str(n2)] + [str(int(rng.integers(0, 10**12)))
                              for _ in range(n2)]
        parts += ["2", "99", "98"]  # unused slot: tokens must be skipped
        lines.append(" ".join(parts))
    lines.insert(7, "1 bad 3 x y z 0 1 5 2 9 9")  # malformed → dropped
    f = tmp_path / "slots.txt"
    f.write_text("\n".join(lines) + "\n")
    p = SlotTextParser(desc)
    got = p.parse_file_columnar(str(f))
    ref = _columnar_from_python(p, str(f), desc.dense_dim)
    assert len(got["label"]) == ref.num_records == 200
    np.testing.assert_array_equal(got["keys"], ref.keys)
    np.testing.assert_array_equal(got["key_slot"], ref.key_slot)
    np.testing.assert_array_equal(got["offsets"], ref.offsets)
    np.testing.assert_allclose(got["dense"], ref.dense, rtol=1e-6)
    np.testing.assert_array_equal(got["label"], ref.label)


@requires_native
def test_dataset_native_load_matches_record_path(tmp_path):
    files = generate_criteo_files(str(tmp_path), num_files=2,
                                  rows_per_file=300, vocab_per_slot=50,
                                  seed=9)
    desc = DataFeedDesc.criteo(batch_size=64)

    def load(native: bool):
        with flags_scope(native_parse=native):
            ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
            ds.set_filelist(files)
            ds.set_thread(2)
            ds.load_into_memory()
            ds.columnarize()
            return ds

    a, b = load(True), load(False)
    assert a.columnar.num_records == b.columnar.num_records
    # same multiset of records (thread interleaving may reorder files)
    ka = np.sort(a.columnar.keys)
    kb = np.sort(b.columnar.keys)
    np.testing.assert_array_equal(ka, kb)
    np.testing.assert_allclose(np.sort(a.columnar.label),
                               np.sort(b.columnar.label))
    # batches build fine from the native-loaded store
    batch = next(a.batches())
    assert batch.num_keys == 64 * 26 and batch.segments_trivial

@requires_native
def test_criteo_extra_tabs_and_bad_hex(tmp_path):
    """Lines with >=40 tabs must be skipped (not crash — regression for a
    stack OOB write); invalid/overlong hex must match python exactly."""
    good = "1\t" + "\t".join(str(i) for i in range(1, 14)) + "\t" + \
        "\t".join(f"{i:x}" for i in range(26))
    bad_hex = good.replace("\t0\t", "\tzz\t", 1)           # invalid hex
    overlong = good + "ffffffffffffffffff"                 # >16 hex digits
    many_tabs = good + "\t" * 5
    f = tmp_path / "edge.txt"
    f.write_text("\n".join([good, many_tabs, bad_hex, overlong]) + "\n")
    desc = DataFeedDesc.criteo(batch_size=4)
    p = CriteoParser(desc)
    got = p.parse_file_columnar(str(f))
    ref = _columnar_from_python(p, str(f), desc.dense_dim)
    assert len(got["label"]) == ref.num_records == 3  # many_tabs dropped
    np.testing.assert_array_equal(got["keys"], ref.keys)


@requires_native
def test_slot_text_truncated_line_no_bleed(tmp_path):
    """A line truncated mid-record must be dropped without consuming the
    NEXT line's tokens (regression: strtol skipping '\\n')."""
    slots = [SlotDef("label", "float", 1), SlotDef("s1", "uint64"),
             SlotDef("s2", "uint64")]
    desc = DataFeedDesc(slots=slots, batch_size=4, label_slot="label")
    lines = [
        "1 1 2 10 20 1 30",      # ok: label=1, s1=[10,20], s2=[30]
        "1 0 1 40",              # truncated: missing s2 group entirely
        "1 1 2 50 60 1 70",      # ok — must NOT be consumed by line 2
    ]
    f = tmp_path / "trunc.txt"
    f.write_text("\n".join(lines) + "\n")
    p = SlotTextParser(desc)
    got = p.parse_file_columnar(str(f))
    ref = _columnar_from_python(p, str(f), desc.dense_dim)
    assert len(got["label"]) == ref.num_records == 2
    np.testing.assert_array_equal(got["keys"], ref.keys)
    np.testing.assert_array_equal(got["offsets"], ref.offsets)


@requires_native
def test_token_garbage_parity(tmp_path):
    """Trailing-garbage tokens ('1x' label, '2.5' count) must be rejected
    by the native path exactly like the python parsers; empty clk group
    must yield clk=0.0 on both paths."""
    good = "1\t" + "\t".join(str(i) for i in range(1, 14)) + "\t" + \
        "\t".join(f"{i:x}" for i in range(26))
    bad_label = good.replace("1\t", "1x\t", 1)
    bad_dense = good.replace("\t3\t", "\t3x\t", 1)
    f = tmp_path / "garb.txt"
    f.write_text("\n".join([good, bad_label, bad_dense]) + "\n")
    desc = DataFeedDesc.criteo(batch_size=4)
    p = CriteoParser(desc)
    got = p.parse_file_columnar(str(f))
    ref = _columnar_from_python(p, str(f), desc.dense_dim)
    assert len(got["label"]) == ref.num_records
    np.testing.assert_allclose(got["dense"], ref.dense, rtol=1e-6)
    assert got["dropped"] == 3 - ref.num_records

    slots = [SlotDef("label", "float", 1), SlotDef("clk", "float", 1),
             SlotDef("s1", "uint64")]
    desc2 = DataFeedDesc(slots=slots, batch_size=4, label_slot="label",
                         clk_slot="clk")
    lines = [
        "1 1 1 0.0 1 5",      # normal
        "1 1 0 1 5",          # clk group PRESENT but empty → clk must be 0
        "2.5 1 1 1 1 5",      # float count → dropped
        "1 1 1 1 1 5x",       # trailing-garbage key → dropped
    ]
    f2 = tmp_path / "slots.txt"
    f2.write_text("\n".join(lines) + "\n")
    p2 = SlotTextParser(desc2)
    got2 = p2.parse_file_columnar(str(f2))
    ref2 = _columnar_from_python(p2, str(f2), desc2.dense_dim)
    assert len(got2["label"]) == ref2.num_records == 2
    np.testing.assert_array_equal(got2["clk"], ref2.clk)
    assert got2["clk"][1] == 0.0


@requires_native
def test_criteo_hex_form_parity(tmp_path):
    """Hex forms int(v,16) would take but parse_hex64 rejects ('0x..',
    '+1a') must map to the sentinel on BOTH paths."""
    base = "1\t" + "\t".join(str(i) for i in range(1, 14)) + "\t"
    cats = [f"{i:x}" for i in range(26)]
    cats[0] = "0x1a"
    cats[1] = "+1a"
    f = tmp_path / "hexforms.txt"
    f.write_text(base + "\t".join(cats) + "\n")
    desc = DataFeedDesc.criteo(batch_size=2)
    p = CriteoParser(desc)
    got = p.parse_file_columnar(str(f))
    ref = _columnar_from_python(p, str(f), desc.dense_dim)
    np.testing.assert_array_equal(got["keys"], ref.keys)
    sent = (np.uint64(1) << np.uint64(52)) | np.uint64(0xFFFFFFFF)
    assert got["keys"][0] == sent


@requires_native
def test_uint64_overflow_and_hexfloat_parity(tmp_path):
    """Over-range uint64 tokens and hex-float labels must be DROPPED by
    both paths (python raises OverflowError/ValueError; native checks
    ERANGE / hex markers)."""
    slots = [SlotDef("label", "float", 1), SlotDef("s1", "uint64")]
    desc = DataFeedDesc(slots=slots, batch_size=4, label_slot="label")
    lines = [
        "1 1 1 5",                          # ok
        "1 1 1 18446744073709551616",       # 2^64: over-range → drop
        "1 0x1p1 1 5",                      # hex-float label → drop
    ]
    f = tmp_path / "ovf.txt"
    f.write_text("\n".join(lines) + "\n")
    p = SlotTextParser(desc)
    got = p.parse_file_columnar(str(f))
    ref = _columnar_from_python(p, str(f), desc.dense_dim)
    assert len(got["label"]) == ref.num_records == 1
    np.testing.assert_array_equal(got["keys"], ref.keys)


def _real_criteo_fixture(path, rows=384, seed=7):
    """A fixture file with REAL Criteo day-file quirks (the reference's
    tolerant MultiSlot parse semantics, data_feed.cc): 8-hex-digit
    lowercase feature hashes, EMPTY dense fields, NEGATIVE ints in I2
    (present in the real dataset), EMPTY categorical fields (missing →
    sentinel), rows ending in an empty field (trailing tab), plus
    malformed lines (wrong field count / garbage label) that must drop."""
    rng = np.random.default_rng(seed)
    lines = []
    for r in range(rows):
        label = str(int(rng.random() < 0.3))
        dense = [str(int(v)) for v in rng.integers(0, 1500, size=13)]
        dense[1] = str(int(rng.integers(-3, 10)))   # I2 goes negative
        for i in rng.choice(13, size=4, replace=False):
            dense[i] = ""                            # missing dense
        cats = [format(int(v), "08x")
                for v in rng.integers(0, 1 << 32, size=26)]
        for i in rng.choice(25, size=2, replace=False):
            cats[i] = ""                             # missing categorical
        cats[25] = ""                                # trailing tab
        lines.append("\t".join([label] + dense + cats))
    # interleave malformed rows: all must be dropped, no bleed
    lines.insert(0, "")                              # blank line
    lines.insert(5, "\t".join(["1"] + ["1"] * 12))   # too few fields
    lines.insert(9, "abc\t" + "\t".join(["1"] * 39)) # garbage label
    path.write_text("\n".join(lines) + "\n")
    return rows


def test_real_criteo_fixture_end_to_end(tmp_path):
    """Real-format quirks parse through DataFeedDesc.criteo → columnar →
    one resident train step (VERDICT r4 item 9)."""
    import optax

    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.train import Trainer

    f = tmp_path / "day_quirks.txt"
    rows = _real_criteo_fixture(f)
    desc = DataFeedDesc.criteo(batch_size=128)
    desc.key_bucket_min = 4096

    # both parse paths agree line-for-line on the quirk fixture
    p = CriteoParser(desc)
    ref = _columnar_from_python(p, str(f), desc.dense_dim)
    assert ref.num_records == rows          # malformed lines dropped
    if load_native() is not None:
        got = p.parse_file_columnar(str(f))
        assert got["dropped"] == 3
        np.testing.assert_array_equal(got["keys"], ref.keys)
        np.testing.assert_allclose(got["dense"], ref.dense, rtol=1e-6)
        np.testing.assert_array_equal(got["label"], ref.label)

    # missing categoricals land on the slot-salted sentinel, missing /
    # negative dense on 0 (log1p clamps at 0)
    sent_low = np.uint64(0xFFFFFFFF)
    mask = (np.uint64(1) << np.uint64(52)) - np.uint64(1)
    assert ((ref.keys & mask) == sent_low).sum() == rows * 3
    assert (ref.dense >= 0).all() and np.isfinite(ref.dense).all()

    # → dataset → columnar → one resident pass on the quirk data
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist([str(f)])
    ds.load_into_memory()
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 15, cfg=cfg,
                           unique_bucket_min=4096)
    tr = Trainer(DeepFM(hidden=(16, 8)), table, desc, tx=optax.adam(1e-2))
    res = tr.train_pass_resident(ds)
    assert res["batches"] == rows // 128
    assert np.isfinite(res["auc"])
    assert tr.table.feature_count > 0
