"""Tier-1 wiring of scripts/online_check.py — the always-on
online-learning soak gate (docs/ONLINE.md): the daemon composition
(train → boundary publish → serving adoption → shrink cycles) holds its
plateau invariants over a reduced horizon, the chaos legs (corrupt
delta, shrink-seam faults) recover through the daemon's own
supervision, and the real-signal subprocess round-trips of
``scripts/onlinelearn.py`` resume bit-consistently with an unkilled
oracle. The full 12-window horizon (3x any other stream test) runs
under the ``slow`` marker; the standalone script is the release gate.
"""

import os
import subprocess
import sys

import pytest

from scripts.online_check import (_run_corrupt_delta_leg, _run_kill_leg,
                                  _run_shrink_chaos_leg, _run_soak_leg,
                                  _run_tiered_lifecycle_leg)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_soak_leg_plateaus_and_is_deterministic(tmp_path):
    """Reduced-horizon soak: 9 windows (3 shrink cycles) of the
    in-process daemon — resident keys, cursor bytes, RSS and staleness
    plateau, every served lookup bit-matches a published version's
    replay oracle, and the whole outcome is seed-deterministic x2."""
    outs = []
    for run in (1, 2):
        wd = str(tmp_path / f"run{run}")
        os.makedirs(wd)
        outs.append(_run_soak_leg(wd, seed=7, windows=9))
    sig = outs[0]["sig"]
    assert sig["windows"] == 9
    assert sig["shrink_cycles"] == 3
    assert sig["shrunk_rows_total"] >= 0
    assert len(sig["versions"]) == 9
    # the plateau is the leg's own assertion; re-state the headline:
    # the last third of the live-row series is flat
    live = sig["live_rows"]
    assert max(live[-3:]) <= max(live[:-3]) * 1.05
    assert outs[0]["queries"] > 0
    assert outs[0]["sig"] == outs[1]["sig"]


def test_tiered_lifecycle_leg_deterministic(tmp_path):
    """Reduced-horizon tiered aging: PassScopedTable → HostStore →
    SsdTier with the async epilogue on — live rows plateau, hot keys
    survive every cycle, and the outcome is deterministic x2."""
    outs = []
    for run in (1, 2):
        wd = str(tmp_path / f"run{run}")
        os.makedirs(wd)
        outs.append(_run_tiered_lifecycle_leg(wd, seed=7, windows=9))
    assert outs[0]["shrunk_total"] > 0
    assert outs[0] == outs[1]


def test_corrupt_delta_recovers_via_forced_base(tmp_path):
    """A flipped-byte delta in the publish feed: the daemon's reload
    loop refuses it loudly and keeps serving; the next shrink cycle's
    forced BASE publish is adopted and staleness returns to zero."""
    out = _run_corrupt_delta_leg(str(tmp_path), seed=7)
    assert out["ok"]
    assert out["recovered_version"] != out["refused_version"]
    assert out["refused_version"] in out["versions"]
    assert out["queries"] > 0


def test_shrink_chaos_retries_then_skips_loudly(tmp_path):
    """The ``online.shrink`` fault seam: a transient failure retries on
    the seeded policy (cycle completes); a hard failure skips the cycle
    loudly (counter + flight-recorder bundle) without stalling."""
    out = _run_shrink_chaos_leg(str(tmp_path), seed=7)
    assert out["transient"]["cycles"] == 3
    assert out["transient"]["skipped"] == 0
    assert out["hard"]["skipped"] == 1
    assert out["hard"]["cycles"] == 2
    for sub in ("transient", "hard"):
        assert out[sub]["fault"]["online.shrink:fail"]["fired"] >= 1


def test_sigterm_roundtrip_replays_open_window(tmp_path):
    """Real SIGTERM on a real ``onlinelearn.py`` process: exit 75 +
    resume marker + mid-window cursor; the relaunch replays the open
    window at-least-once and bit-matches the unkilled oracle at the
    last common window boundary."""
    out = _run_kill_leg(str(tmp_path), seed=7, signame="TERM")
    assert out["ok"] and out["rc"] == 75
    assert out["open_window"]
    assert out["replayed_files"] == len(out["open_window"])
    assert out["boundary_digest"]


def test_sigkill_roundtrip_matches_oracle_exactly(tmp_path):
    """Real SIGKILL: no marker, resume from the last clean boundary —
    the drained daemon's final state bit-matches the unkilled oracle
    EXACTLY (nothing mid-window survived to replay)."""
    out = _run_kill_leg(str(tmp_path), seed=7, signame="KILL")
    assert out["ok"] and out["rc"] == -9
    assert out["open_window"] == []
    assert out["replayed_files"] == 0
    assert out["common_boundary"] == out["final_step"]


@pytest.mark.slow
def test_online_check_full_gate(tmp_path):
    """The full 12-window gate, exactly as released: soak x2 +
    tiered x2 + corrupt delta + shrink chaos + both kill legs."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "online_check.py"),
         "--workdir", str(tmp_path)],
        capture_output=True, text=True, timeout=1800, env=env)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "PASS" in r.stdout
