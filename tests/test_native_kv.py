"""Native C++ kv index: parity vs the python fallback + edge cases."""

import numpy as np
import pytest

from paddlebox_tpu.native import load_native
from paddlebox_tpu.ps.kv import NativeKV, PyKV, TableFullError


requires_native = pytest.mark.skipif(load_native() is None,
                                     reason="native lib unavailable")


@requires_native
def test_native_matches_python_randomized():
    rng = np.random.default_rng(0)
    nat = NativeKV(5000, load_native())
    py = PyKV(5000)
    for _ in range(20):
        keys = rng.integers(0, 3000, size=500).astype(np.uint64)
        np.testing.assert_array_equal(nat.assign(keys), py.assign(keys))
        probe = rng.integers(0, 6000, size=200).astype(np.uint64)
        np.testing.assert_array_equal(nat.lookup(probe), py.lookup(probe))
        rel = rng.integers(0, 3000, size=50).astype(np.uint64)
        # release order of freed rows differs is fine; compare sets + len
        r1, r2 = nat.release(rel), py.release(rel)
        assert sorted(r1.tolist()) == sorted(r2.tolist())
        assert len(nat) == len(py)
    k1, _ = nat.items()
    k2, _ = py.items()
    np.testing.assert_array_equal(np.sort(k1), np.sort(k2))


@requires_native
def test_native_edge_keys_and_reuse():
    nat = NativeKV(8, load_native())
    edge = np.array([0, 1, 2**64 - 1, 2**64 - 2], dtype=np.uint64)
    rows = nat.assign(edge)
    assert len(set(rows.tolist())) == 4
    np.testing.assert_array_equal(nat.assign(edge), rows)  # stable
    np.testing.assert_array_equal(nat.lookup(edge), rows)
    freed = nat.release(edge[:2])
    assert len(freed) == 2 and len(nat) == 2
    # released keys gone; rows recycled for new keys
    assert nat.lookup(edge[:1])[0] == -1
    r_new = nat.assign(np.array([12345], np.uint64))
    assert r_new[0] in freed


@requires_native
def test_native_capacity_exhaustion():
    nat = NativeKV(4, load_native())
    nat.assign(np.arange(4, dtype=np.uint64))
    with pytest.raises(TableFullError):
        nat.assign(np.array([99], np.uint64))
    # failed assign must not corrupt: existing keys still resolve
    assert all(nat.lookup(np.arange(4, dtype=np.uint64)) >= 0)


@requires_native
def test_native_churn_tombstone_rehash():
    """assign/release churn must not exhaust EMPTY slots (probe-loop hang)
    and must keep mappings exact across tombstone-triggered rehashes."""
    nat = NativeKV(64, load_native())
    py = PyKV(64)
    rng = np.random.default_rng(2)
    for round_ in range(200):  # 200*50 >> bucket count → many rehashes
        keys = (rng.integers(0, 2**62, size=50) + round_ * 1000).astype(np.uint64)
        r1, r2 = nat.assign(keys), py.assign(keys)
        assert len(set(r1.tolist())) == len(set(r2.tolist()))
        nat.release(keys)
        py.release(keys)
    assert len(nat) == 0
    # survivors after churn still resolve exactly
    keep = rng.integers(0, 2**62, size=40).astype(np.uint64)
    rows = nat.assign(keep)
    for round_ in range(100):
        junk = (rng.integers(2**62, 2**63, size=20)).astype(np.uint64)
        nat.assign(junk)
        nat.release(junk)
    np.testing.assert_array_equal(nat.lookup(keep), rows)


@requires_native
def test_assign_unique_matches_python():
    """Fused dedup+assign: same row mapping and a consistent inverse on
    both backends (unique ORDER may differ — native is first-occurrence,
    python is sorted — so compare through the maps they induce)."""
    rng = np.random.default_rng(3)
    nat = NativeKV(5000, load_native())
    py = PyKV(5000)
    for _ in range(10):
        keys = rng.integers(0, 800, size=600).astype(np.uint64)
        r1, inv1 = nat.assign_unique(keys)
        r2, inv2 = py.assign_unique(keys)
        assert len(r1) == len(r2) == len(np.unique(keys))
        # rows are dup-free within a call
        assert len(set(r1.tolist())) == len(r1)
        # the induced key→row map agrees with each backend's plain assign
        # (row NUMBERING differs across backends — first-occurrence vs
        # sorted assignment order — so no cross-backend row equality)
        np.testing.assert_array_equal(r1[inv1], nat.assign(keys))
        np.testing.assert_array_equal(r2[inv2], py.assign(keys))


@requires_native
def test_assign_unique_row_reuse_after_release():
    """Epoch scratch must not leak stale seen marks across calls when rows
    are released and reassigned to different keys."""
    nat = NativeKV(64, load_native())
    a = np.array([1, 2, 3], np.uint64)
    r_a, _ = nat.assign_unique(a)
    nat.release(a)
    b = np.array([7, 8, 9, 7], np.uint64)
    r_b, inv_b = nat.assign_unique(b)
    assert sorted(r_b.tolist()) == sorted(r_a.tolist())  # rows recycled
    assert len(r_b) == 3 and inv_b[0] == inv_b[3]
    np.testing.assert_array_equal(r_b[inv_b], nat.lookup(b))


@requires_native
def test_assign_unique_table_full_midway():
    nat = NativeKV(2, load_native())
    with pytest.raises(TableFullError):
        nat.assign_unique(np.array([1, 1, 2, 3], np.uint64))
    # keys assigned before the failure still resolve
    assert nat.lookup(np.array([1], np.uint64))[0] >= 0


@requires_native
def test_lookup_unique_miss_collapse():
    """Unknown keys share one sentinel entry; known keys resolve exactly;
    an all-miss batch yields a single sentinel unique."""
    sent = 9999
    nat = NativeKV(64, load_native())
    py = PyKV(64)
    known = np.array([10, 20, 30], np.uint64)
    nat.assign(known)
    py.assign(known)
    probe = np.array([20, 555, 10, 666, 20, 555], np.uint64)
    r1, inv1 = nat.lookup_unique(probe, sent)
    r2, inv2 = py.lookup_unique(probe, sent)
    np.testing.assert_array_equal(r1[inv1], r2[inv2])
    assert (r1[inv1][[1, 3, 5]] == sent).all()
    # native collapses all misses into one unique slot
    assert (r1 == sent).sum() == 1
    # all-miss batch
    r3, inv3 = nat.lookup_unique(np.array([777, 888], np.uint64), sent)
    assert len(r3) == 1 and r3[0] == sent and (inv3 == 0).all()


def test_pykv_lookup_unique_miss_collapse():
    """Python fallback must honor the same miss-collapse contract as the
    native index (duplicate-free unique rows for the scatter promise)."""
    py = PyKV(64)
    py.assign(np.array([10, 20, 30], np.uint64))
    probe = np.array([20, 555, 10, 666, 20, 555], np.uint64)
    r, inv = py.lookup_unique(probe, 9999)
    assert (r == 9999).sum() == 1            # one shared sentinel entry
    assert len(set(r.tolist())) == len(r)    # duplicate-free
    got = r[inv]
    assert (got[[1, 3, 5]] == 9999).all()
    np.testing.assert_array_equal(
        got[[0, 2, 4]], py.lookup(np.array([20, 10, 20], np.uint64)))
    # all-miss batch
    r2, inv2 = py.lookup_unique(np.array([777, 888], np.uint64), 9999)
    assert len(r2) == 1 and r2[0] == 9999 and (inv2 == 0).all()


def _arena_invariants(kv, chunk_bits, n_slots, keys, slots):
    rows, locs = kv.assign_slotted(keys, slots)
    cs_map, cr_map = kv.arena_export()
    cb = chunk_bits
    # every row decodes back through (slot, local) and the chunk map
    assert (locs >= 0).all()
    chunk_of = rows >> cb
    np.testing.assert_array_equal(cs_map[chunk_of], slots.astype(np.int32))
    recon = (cr_map[chunk_of] << cb) | (rows & ((1 << cb) - 1))
    np.testing.assert_array_equal(recon, locs)
    # stable on re-assign
    rows2, locs2 = kv.assign_slotted(keys, slots)
    np.testing.assert_array_equal(rows, rows2)
    np.testing.assert_array_equal(locs, locs2)
    return rows, locs


@pytest.mark.parametrize("impl", ["native", "py"])
def test_arena_slotted_assign_roundtrip(impl):
    if impl == "native" and load_native() is None:
        pytest.skip("native lib unavailable")
    kv = (NativeKV(1 << 12, load_native()) if impl == "native"
          else PyKV(1 << 12))
    kv.arena_enable(4, 8)  # 16-row chunks, 8 slots
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 500, size=400).astype(np.uint64)
    slots = (keys % 8).astype(np.uint16)  # slot stable per key
    _arena_invariants(kv, 4, 8, keys, slots)


@pytest.mark.parametrize("impl", ["native", "py"])
def test_arena_foreign_row_flags_minus_one(impl):
    if impl == "native" and load_native() is None:
        pytest.skip("native lib unavailable")
    kv = (NativeKV(256, load_native()) if impl == "native" else PyKV(256))
    kv.arena_enable(4, 4)
    k = np.array([7, 8], np.uint64)
    kv.assign(k)  # slotless → default arena
    rows, locs = kv.assign_slotted(k, np.array([1, 2], np.uint16))
    assert (locs == -1).all()  # foreign rows are flagged, not mislabeled
    # fresh keys under the right slot are fine
    rows2, locs2 = kv.assign_slotted(np.array([9], np.uint64),
                                     np.array([1], np.uint16))
    assert locs2[0] >= 0


@pytest.mark.parametrize("impl", ["native", "py"])
def test_arena_release_reuses_within_slot(impl):
    if impl == "native" and load_native() is None:
        pytest.skip("native lib unavailable")
    kv = (NativeKV(256, load_native()) if impl == "native" else PyKV(256))
    kv.arena_enable(3, 4)
    keys = np.arange(20, dtype=np.uint64)
    slots = np.full(20, 2, np.uint16)
    rows, _ = kv.assign_slotted(keys, slots)
    kv.release(keys[:5])
    nk = np.arange(100, 105, dtype=np.uint64)
    nrows, nlocs = kv.assign_slotted(nk, np.full(5, 2, np.uint16))
    assert set(nrows.tolist()) == set(rows[:5].tolist())  # reused in-slot
    assert (nlocs >= 0).all()


@pytest.mark.parametrize("impl", ["native", "py"])
def test_arena_assign_unique_slotted(impl):
    if impl == "native" and load_native() is None:
        pytest.skip("native lib unavailable")
    kv = (NativeKV(1 << 10, load_native()) if impl == "native"
          else PyKV(1 << 10))
    kv.arena_enable(4, 4)
    keys = np.array([5, 9, 5, 13, 9, 5], np.uint64)
    slots = np.array([1, 2, 1, 3, 2, 1], np.uint16)
    uniq_rows, inv = kv.assign_unique_slotted(keys, slots)
    assert len(uniq_rows) == 3
    np.testing.assert_array_equal(uniq_rows[inv],
                                  kv.assign_slotted(keys, slots)[0])
    # rows landed in their slots' arenas
    cs_map, _ = kv.arena_export()
    _, locs = kv.assign_slotted(keys, slots)
    assert (locs >= 0).all()


def test_arena_enable_after_assign_raises():
    kv = PyKV(64)
    kv.assign(np.array([1], np.uint64))
    with pytest.raises(RuntimeError):
        kv.arena_enable(4, 4)
    if load_native() is not None:
        nv = NativeKV(64, load_native())
        nv.assign(np.array([1], np.uint64))
        with pytest.raises(RuntimeError):
            nv.arena_enable(4, 4)


@pytest.mark.parametrize("impl", ["native", "py"])
def test_arena_out_of_range_slot_clamps_to_default(impl):
    """Slot ids >= n_slots must clamp to the default arena (local = -1),
    never index out of bounds."""
    if impl == "native" and load_native() is None:
        pytest.skip("native lib unavailable")
    kv = (NativeKV(256, load_native()) if impl == "native" else PyKV(256))
    kv.arena_enable(4, 4)
    rows, locs = kv.assign_slotted(np.array([1, 2], np.uint64),
                                   np.array([100, 4], np.uint16))
    assert (locs == -1).all()
    assert (rows >= 0).all()
    # in-range keys still work afterwards (no corruption)
    r2, l2 = kv.assign_slotted(np.array([3], np.uint64),
                               np.array([1], np.uint16))
    assert l2[0] >= 0 and len(kv) == 3
