"""Native C++ kv index: parity vs the python fallback + edge cases."""

import numpy as np
import pytest

from paddlebox_tpu.native import load_native
from paddlebox_tpu.ps.kv import NativeKV, PyKV, TableFullError


requires_native = pytest.mark.skipif(load_native() is None,
                                     reason="native lib unavailable")


@requires_native
def test_native_matches_python_randomized():
    rng = np.random.default_rng(0)
    nat = NativeKV(5000, load_native())
    py = PyKV(5000)
    for _ in range(20):
        keys = rng.integers(0, 3000, size=500).astype(np.uint64)
        np.testing.assert_array_equal(nat.assign(keys), py.assign(keys))
        probe = rng.integers(0, 6000, size=200).astype(np.uint64)
        np.testing.assert_array_equal(nat.lookup(probe), py.lookup(probe))
        rel = rng.integers(0, 3000, size=50).astype(np.uint64)
        # release order of freed rows differs is fine; compare sets + len
        r1, r2 = nat.release(rel), py.release(rel)
        assert sorted(r1.tolist()) == sorted(r2.tolist())
        assert len(nat) == len(py)
    k1, _ = nat.items()
    k2, _ = py.items()
    np.testing.assert_array_equal(np.sort(k1), np.sort(k2))


@requires_native
def test_native_edge_keys_and_reuse():
    nat = NativeKV(8, load_native())
    edge = np.array([0, 1, 2**64 - 1, 2**64 - 2], dtype=np.uint64)
    rows = nat.assign(edge)
    assert len(set(rows.tolist())) == 4
    np.testing.assert_array_equal(nat.assign(edge), rows)  # stable
    np.testing.assert_array_equal(nat.lookup(edge), rows)
    freed = nat.release(edge[:2])
    assert len(freed) == 2 and len(nat) == 2
    # released keys gone; rows recycled for new keys
    assert nat.lookup(edge[:1])[0] == -1
    r_new = nat.assign(np.array([12345], np.uint64))
    assert r_new[0] in freed


@requires_native
def test_native_capacity_exhaustion():
    nat = NativeKV(4, load_native())
    nat.assign(np.arange(4, dtype=np.uint64))
    with pytest.raises(TableFullError):
        nat.assign(np.array([99], np.uint64))
    # failed assign must not corrupt: existing keys still resolve
    assert all(nat.lookup(np.arange(4, dtype=np.uint64)) >= 0)


@requires_native
def test_native_churn_tombstone_rehash():
    """assign/release churn must not exhaust EMPTY slots (probe-loop hang)
    and must keep mappings exact across tombstone-triggered rehashes."""
    nat = NativeKV(64, load_native())
    py = PyKV(64)
    rng = np.random.default_rng(2)
    for round_ in range(200):  # 200*50 >> bucket count → many rehashes
        keys = (rng.integers(0, 2**62, size=50) + round_ * 1000).astype(np.uint64)
        r1, r2 = nat.assign(keys), py.assign(keys)
        assert len(set(r1.tolist())) == len(set(r2.tolist()))
        nat.release(keys)
        py.release(keys)
    assert len(nat) == 0
    # survivors after churn still resolve exactly
    keep = rng.integers(0, 2**62, size=40).astype(np.uint64)
    rows = nat.assign(keep)
    for round_ in range(100):
        junk = (rng.integers(2**62, 2**63, size=20)).astype(np.uint64)
        nat.assign(junk)
        nat.release(junk)
    np.testing.assert_array_equal(nat.lookup(keep), rows)


@requires_native
def test_assign_unique_matches_python():
    """Fused dedup+assign: same row mapping and a consistent inverse on
    both backends (unique ORDER may differ — native is first-occurrence,
    python is sorted — so compare through the maps they induce)."""
    rng = np.random.default_rng(3)
    nat = NativeKV(5000, load_native())
    py = PyKV(5000)
    for _ in range(10):
        keys = rng.integers(0, 800, size=600).astype(np.uint64)
        r1, inv1 = nat.assign_unique(keys)
        r2, inv2 = py.assign_unique(keys)
        assert len(r1) == len(r2) == len(np.unique(keys))
        # rows are dup-free within a call
        assert len(set(r1.tolist())) == len(r1)
        # the induced key→row map agrees with each backend's plain assign
        # (row NUMBERING differs across backends — first-occurrence vs
        # sorted assignment order — so no cross-backend row equality)
        np.testing.assert_array_equal(r1[inv1], nat.assign(keys))
        np.testing.assert_array_equal(r2[inv2], py.assign(keys))


@requires_native
def test_assign_unique_row_reuse_after_release():
    """Epoch scratch must not leak stale seen marks across calls when rows
    are released and reassigned to different keys."""
    nat = NativeKV(64, load_native())
    a = np.array([1, 2, 3], np.uint64)
    r_a, _ = nat.assign_unique(a)
    nat.release(a)
    b = np.array([7, 8, 9, 7], np.uint64)
    r_b, inv_b = nat.assign_unique(b)
    assert sorted(r_b.tolist()) == sorted(r_a.tolist())  # rows recycled
    assert len(r_b) == 3 and inv_b[0] == inv_b[3]
    np.testing.assert_array_equal(r_b[inv_b], nat.lookup(b))


@requires_native
def test_assign_unique_table_full_midway():
    nat = NativeKV(2, load_native())
    with pytest.raises(TableFullError):
        nat.assign_unique(np.array([1, 1, 2, 3], np.uint64))
    # keys assigned before the failure still resolve
    assert nat.lookup(np.array([1], np.uint64))[0] >= 0


@requires_native
def test_lookup_unique_miss_collapse():
    """Unknown keys share one sentinel entry; known keys resolve exactly;
    an all-miss batch yields a single sentinel unique."""
    sent = 9999
    nat = NativeKV(64, load_native())
    py = PyKV(64)
    known = np.array([10, 20, 30], np.uint64)
    nat.assign(known)
    py.assign(known)
    probe = np.array([20, 555, 10, 666, 20, 555], np.uint64)
    r1, inv1 = nat.lookup_unique(probe, sent)
    r2, inv2 = py.lookup_unique(probe, sent)
    np.testing.assert_array_equal(r1[inv1], r2[inv2])
    assert (r1[inv1][[1, 3, 5]] == sent).all()
    # native collapses all misses into one unique slot
    assert (r1 == sent).sum() == 1
    # all-miss batch
    r3, inv3 = nat.lookup_unique(np.array([777, 888], np.uint64), sent)
    assert len(r3) == 1 and r3[0] == sent and (inv3 == 0).all()


def test_pykv_lookup_unique_miss_collapse():
    """Python fallback must honor the same miss-collapse contract as the
    native index (duplicate-free unique rows for the scatter promise)."""
    py = PyKV(64)
    py.assign(np.array([10, 20, 30], np.uint64))
    probe = np.array([20, 555, 10, 666, 20, 555], np.uint64)
    r, inv = py.lookup_unique(probe, 9999)
    assert (r == 9999).sum() == 1            # one shared sentinel entry
    assert len(set(r.tolist())) == len(r)    # duplicate-free
    got = r[inv]
    assert (got[[1, 3, 5]] == 9999).all()
    np.testing.assert_array_equal(
        got[[0, 2, 4]], py.lookup(np.array([20, 10, 20], np.uint64)))
    # all-miss batch
    r2, inv2 = py.lookup_unique(np.array([777, 888], np.uint64), 9999)
    assert len(r2) == 1 and r2[0] == 9999 and (inv2 == 0).all()
