"""Native C++ kv index: parity vs the python fallback + edge cases."""

import numpy as np
import pytest

from paddlebox_tpu.native import load_native
from paddlebox_tpu.ps.kv import NativeKV, PyKV, TableFullError


requires_native = pytest.mark.skipif(load_native() is None,
                                     reason="native lib unavailable")


@requires_native
def test_native_matches_python_randomized():
    rng = np.random.default_rng(0)
    nat = NativeKV(5000, load_native())
    py = PyKV(5000)
    for _ in range(20):
        keys = rng.integers(0, 3000, size=500).astype(np.uint64)
        np.testing.assert_array_equal(nat.assign(keys), py.assign(keys))
        probe = rng.integers(0, 6000, size=200).astype(np.uint64)
        np.testing.assert_array_equal(nat.lookup(probe), py.lookup(probe))
        rel = rng.integers(0, 3000, size=50).astype(np.uint64)
        # release order of freed rows differs is fine; compare sets + len
        r1, r2 = nat.release(rel), py.release(rel)
        assert sorted(r1.tolist()) == sorted(r2.tolist())
        assert len(nat) == len(py)
    k1, _ = nat.items()
    k2, _ = py.items()
    np.testing.assert_array_equal(np.sort(k1), np.sort(k2))


@requires_native
def test_native_edge_keys_and_reuse():
    nat = NativeKV(8, load_native())
    edge = np.array([0, 1, 2**64 - 1, 2**64 - 2], dtype=np.uint64)
    rows = nat.assign(edge)
    assert len(set(rows.tolist())) == 4
    np.testing.assert_array_equal(nat.assign(edge), rows)  # stable
    np.testing.assert_array_equal(nat.lookup(edge), rows)
    freed = nat.release(edge[:2])
    assert len(freed) == 2 and len(nat) == 2
    # released keys gone; rows recycled for new keys
    assert nat.lookup(edge[:1])[0] == -1
    r_new = nat.assign(np.array([12345], np.uint64))
    assert r_new[0] in freed


@requires_native
def test_native_capacity_exhaustion():
    nat = NativeKV(4, load_native())
    nat.assign(np.arange(4, dtype=np.uint64))
    with pytest.raises(TableFullError):
        nat.assign(np.array([99], np.uint64))
    # failed assign must not corrupt: existing keys still resolve
    assert all(nat.lookup(np.arange(4, dtype=np.uint64)) >= 0)


@requires_native
def test_native_churn_tombstone_rehash():
    """assign/release churn must not exhaust EMPTY slots (probe-loop hang)
    and must keep mappings exact across tombstone-triggered rehashes."""
    nat = NativeKV(64, load_native())
    py = PyKV(64)
    rng = np.random.default_rng(2)
    for round_ in range(200):  # 200*50 >> bucket count → many rehashes
        keys = (rng.integers(0, 2**62, size=50) + round_ * 1000).astype(np.uint64)
        r1, r2 = nat.assign(keys), py.assign(keys)
        assert len(set(r1.tolist())) == len(set(r2.tolist()))
        nat.release(keys)
        py.release(keys)
    assert len(nat) == 0
    # survivors after churn still resolve exactly
    keep = rng.integers(0, 2**62, size=40).astype(np.uint64)
    rows = nat.assign(keep)
    for round_ in range(100):
        junk = (rng.integers(2**62, 2**63, size=20)).astype(np.uint64)
        nat.assign(junk)
        nat.release(junk)
    np.testing.assert_array_equal(nat.lookup(keep), rows)
