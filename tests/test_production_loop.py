"""THE production topology in one test: tiered sharded PS (persistent
windows, delta staging, overlapped pre-build) × mesh RESIDENT passes ×
metric-variant registry × base+delta checkpoints × cold restore.

Every piece has its own test file; this one proves they COMPOSE — the
loop a reference user actually runs (SURVEY.md §3.3 pass pipelining +
§3.4 checkpointing + §3.5 metrics), at pod scale on the 8-device CPU
mesh."""

import numpy as np
import jax
import optax
import pytest

from paddlebox_tpu.config import flags_scope
from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.ps import (BoxPSHelper, SparseSGDConfig,
                              TieredShardedEmbeddingTable)
from paddlebox_tpu.train.checkpoint import CheckpointManager
from paddlebox_tpu.train.sharded import ShardedTrainer

N = 8


ROWS, BS = 640, 32


def _mk_pass(tmp_path, p, vocab=60, step=15):
    """Sliding key ranges (value_base): consecutive passes share ~75%
    of their feature space, so delta staging has real reuse."""
    files = generate_criteo_files(
        str(tmp_path / f"pp{p}"), num_files=1, rows_per_file=ROWS,
        vocab_per_slot=vocab, seed=900 + p, value_base=p * step)
    desc = DataFeedDesc.criteo(batch_size=BS)
    desc.key_bucket_min = 1024
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()
    return ds, desc


def _mk_trainer(desc, mesh):
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.1, mf_learning_rate=0.1)
    table = TieredShardedEmbeddingTable(
        N, mf_dim=4, capacity_per_shard=2048, cfg=cfg,
        req_bucket_min=256, serve_bucket_min=256)
    with flags_scope(log_period_steps=10000):
        tr = ShardedTrainer(DeepFM(hidden=(16, 16)), table, desc, mesh,
                            tx=optax.adam(2e-3), seed=11)
    tr.metrics.init_metric("auc2", method="auc")
    tr.metrics.init_metric("wu", method="wuauc")
    return table, tr, BoxPSHelper(table, trainer=tr)


@pytest.mark.slow
def test_production_loop_composes_and_restores(tmp_path):
    assert len(jax.devices()) >= N
    mesh = make_mesh(N)
    built = [_mk_pass(tmp_path, p) for p in range(4)]
    desc = built[0][1]

    def run(n_passes, cm_root, resume=False):
        table, tr, helper = _mk_trainer(desc, mesh)
        cm = CheckpointManager(str(cm_root), keep=10)
        start = 0
        if resume:
            restored = cm.restore(tr)
            assert restored is not None
            # every pass has the same global-batch count, and only the
            # resident passes advance global_step
            nb_per_pass = (-(-ROWS // BS) + N - 1) // N  # ceil(ceil(R/B)/N)
            start = restored // nb_per_pass
        outs = []
        for p in range(start, n_passes):
            ds = built[p][0]
            helper.begin_pass(ds)
            st = dict(table.last_pass_stats)
            if p + 1 < n_passes:
                helper.stage_pass(built[p + 1][0])  # overlapped pre-build
            res = tr.train_pass_resident(ds)        # mesh RESIDENT pass
            helper.end_pass(ds)
            cm.save(tr, delta=(p > 0))              # base then delta chain
            outs.append((res, st))
        return table, tr, outs

    # uninterrupted 4-pass run
    ta, tra, outs_a = run(4, tmp_path / "cma")
    # interrupted run: 2 passes, then a COLD restore (fresh table,
    # trainer, registry — the replacement process) continues 2 more
    run(2, tmp_path / "cmb")
    tb, trb, outs_b = run(4, tmp_path / "cmb", resume=True)

    # delta staging engaged: later passes stage only NEW keys while
    # the overlap stays resident
    for res, st in outs_a[1:]:
        assert st["staged"] > 0 and st["resident"] > 0, st
    # resident-pass registry accumulated on the mesh
    assert tra.metrics.get_metric_msg("auc2")["ins_num"] > 0
    assert np.isfinite(tra.metrics.get_metric_msg("wu")["wuauc"])

    # the restored run's final state matches the uninterrupted run's
    ra, rb = outs_a[-1][0], outs_b[-1][0]
    assert rb["ins_num"] == ra["ins_num"]
    assert np.isclose(rb["auc"], ra["auc"], atol=1e-6), (ra["auc"],
                                                         rb["auc"])
    for x, y in zip(jax.tree.leaves(tra.state.params),
                    jax.tree.leaves(trb.state.params)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-6, atol=1e-7)
    # host-tier content matches per shard (the full model)
    for s in range(N):
        ka, _ = ta.hosts[s].index.items()
        kb, _ = tb.hosts[s].index.items()
        np.testing.assert_array_equal(np.sort(ka), np.sort(kb))
        a = ta.hosts[s].fetch(np.sort(ka))
        b = tb.hosts[s].fetch(np.sort(ka))
        np.testing.assert_allclose(b["embed_w"], a["embed_w"],
                                   rtol=1e-6, atol=1e-8)
