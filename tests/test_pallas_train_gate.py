"""Tier-1 bit-closeness gates for the Pallas kernel dispatch (ISSUE 12).

A seeded resident train job with ``use_pallas_seqpool=True`` (interpret
mode on this CPU mesh) must reproduce the default XLA composition's
logical state:

- UNIFORM (trivial one-key-per-slot layout): the pool is a reshape on
  both paths, so the ``state_digest`` must match EXACTLY — and this also
  pins the inverse guarantee that default flags keep today's program.
- ZIPF/ragged (real segment streams): the MXU one-hot pooling sums in a
  different order than XLA's scatter-add, so the gate is numeric — table
  rows (pushed grads applied in-table) and dense params within the
  documented f32 tolerance (docs/PERFORMANCE.md §Device kernels:
  rtol 2e-4 against per-step ~1e-6 drift compounding over two passes).
- ``use_pallas_gather=True`` (the table.py line-gather): gather_rows
  returns the identical lines bitwise, so the digest must match EXACTLY.
"""

import json
import os

import jax
import numpy as np
import optax
import pytest

from paddlebox_tpu.config import flags_scope
from paddlebox_tpu.data import DataFeedDesc, DatasetFactory, SlotDef
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
from paddlebox_tpu.train import Trainer
from paddlebox_tpu.train.checkpoint import state_digest

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                         os.pardir))


@pytest.fixture(scope="module")
def criteo_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("criteo_pallas_gate")
    return generate_criteo_files(str(d), num_files=1, rows_per_file=600,
                                 vocab_per_slot=40, seed=21)


def _trainer_uniform(files, bs=200):
    desc = DataFeedDesc.criteo(batch_size=bs)
    desc.key_bucket_min = 512
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.set_thread(1)
    ds.load_into_memory()
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.05, mf_learning_rate=0.05)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 12, cfg=cfg,
                           unique_bucket_min=512)
    tr = Trainer(DeepFM(hidden=(16, 8)), table, desc, tx=optax.adam(1e-2),
                 seed=3)
    return tr, ds


def _ragged_records(n=400, num_slots=4, seed=0):
    """Zipf-ragged multi-key slots — the non-trivial segment stream that
    actually exercises the fused pooling kernel."""
    from paddlebox_tpu.data.record import SlotRecord
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        counts = np.minimum(rng.zipf(1.5, size=num_slots), 8)
        counts[rng.integers(0, num_slots)] = max(
            1, counts[rng.integers(0, num_slots)])
        offs = np.zeros(num_slots + 1, np.int32)
        np.cumsum(counts, out=offs[1:])
        keys = rng.integers(0, 3000, size=int(offs[-1])).astype(np.uint64)
        recs.append(SlotRecord(
            keys=keys, slot_offsets=offs,
            dense=rng.normal(size=3).astype(np.float32),
            label=float(i % 2), show=1.0, clk=float(i % 2)))
    return recs


def _trainer_ragged(bs=64, seed=0):
    from paddlebox_tpu.data import InMemoryDataset
    slots = [SlotDef("label", "float", 1), SlotDef("d", "float", 3)]
    slots += [SlotDef(f"S{i}", "uint64") for i in range(4)]
    desc = DataFeedDesc(slots=slots, label_slot="label", batch_size=bs,
                        key_bucket_min=512)
    ds = InMemoryDataset(desc)
    ds.records = _ragged_records(seed=seed)
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.05, mf_learning_rate=0.05)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 12, cfg=cfg,
                           unique_bucket_min=512)
    tr = Trainer(DeepFM(hidden=(16, 8)), table, desc, tx=optax.adam(1e-2),
                 seed=3)
    return tr, ds


def _logical_state(tr):
    """(sorted keys, host row blob, param leaves) — the numeric form of
    state_digest, comparable with a tolerance."""
    tr.sync_table()
    with tr.table.host_lock:
        keys, rows = tr.table.index.items()
    order = np.argsort(keys)
    blob = tr.table._gather_host(rows[order])
    leaves = [np.asarray(l) for l in jax.tree.leaves(
        jax.device_get(tr.state.params))]
    return keys[order], blob, leaves


def test_uniform_trivial_layout_digest_exact(criteo_files):
    """Trivial layout: the flag leaves the reshape fast path alone —
    the whole seeded train job is byte-for-byte identical."""
    with flags_scope(use_pallas_seqpool=False):
        tr0, ds = _trainer_uniform(criteo_files)
        tr0.train_pass(ds)
        d0 = state_digest(tr0)
    with flags_scope(use_pallas_seqpool=True):
        tr1, ds = _trainer_uniform(criteo_files)
        tr1.train_pass(ds)
        d1 = state_digest(tr1)
    assert d0 == d1


def test_pallas_gather_digest_exact(criteo_files):
    """use_pallas_gather=True (the already-wired table.py line-gather):
    gather_rows is bitwise a gather, so the digest matches exactly."""
    with flags_scope(use_pallas_gather=False):
        tr0, ds = _trainer_uniform(criteo_files)
        tr0.train_pass(ds)
        d0 = state_digest(tr0)
    with flags_scope(use_pallas_gather=True):
        tr1, ds = _trainer_uniform(criteo_files)
        tr1.train_pass(ds)
        d1 = state_digest(tr1)
    assert d0 == d1


def test_zipf_ragged_state_close(criteo_files):
    """Zipf-ragged resident train, two passes: fused Pallas pooling vs
    the XLA composition — same keys, table rows and dense params within
    the documented f32 tolerance (forward pooled outputs and the pushed
    grads both ride this: the table rows ARE the accumulated pushes)."""
    def run(flag):
        with flags_scope(use_pallas_seqpool=flag):
            tr, ds = _trainer_ragged()
            tr.train_pass(ds)
            tr.train_pass(ds)
            return _logical_state(tr)

    k0, b0, p0 = run(False)
    k1, b1, p1 = run(True)
    np.testing.assert_array_equal(k0, k1)
    for f in sorted(b0):
        np.testing.assert_allclose(
            b1[f], b0[f], rtol=2e-4, atol=2e-5,
            err_msg=f"table field {f} diverged beyond f32 tolerance")
    for a, b in zip(p0, p1):
        np.testing.assert_allclose(b, a, rtol=2e-4, atol=2e-5)


def test_committed_kernel_trajectory_gates():
    """The interpret-mode CPU kernel round is recorded (satellite:
    kernel.* rows live in BENCH_trajectory.json) and the perf gate
    passes over it."""
    import importlib.util
    path = os.path.join(REPO_ROOT, "BENCH_trajectory.json")
    with open(path) as fh:
        data = json.load(fh)
    metrics = {r["metric"] for r in data["rows"]}
    for probe in ("gather", "pool_cvm", "fused"):
        assert any(m.startswith(f"kernel.{probe}.") and m.endswith(".cpu")
                   for m in metrics), f"no recorded kernel.{probe}.* row"
    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(REPO_ROOT, "scripts", "perf_gate.py"))
    pg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pg)
    assert pg.check(path, ignore_live=True) == 0
