"""Tier-1 bit-closeness gates for the Pallas kernel dispatch (ISSUE 12).

A seeded resident train job with ``use_pallas_seqpool=True`` (interpret
mode on this CPU mesh) must reproduce the default XLA composition's
logical state:

- UNIFORM (trivial one-key-per-slot layout): the pool is a reshape on
  both paths, so the ``state_digest`` must match EXACTLY — and this also
  pins the inverse guarantee that default flags keep today's program.
- ZIPF/ragged (real segment streams): the MXU one-hot pooling sums in a
  different order than XLA's scatter-add, so the gate is numeric — table
  rows (pushed grads applied in-table) and dense params within the
  documented f32 tolerance (docs/PERFORMANCE.md §Device kernels:
  rtol 2e-4 against per-step ~1e-6 drift compounding over two passes).
- ``use_pallas_gather=True`` (the table.py line-gather): gather_rows
  returns the identical lines bitwise, so the digest must match EXACTLY.
"""

import json
import os

import jax
import numpy as np
import optax
import pytest

from paddlebox_tpu.config import flags_scope
from paddlebox_tpu.data import DataFeedDesc, DatasetFactory, SlotDef
from paddlebox_tpu.data.criteo import generate_criteo_files
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
from paddlebox_tpu.train import Trainer
from paddlebox_tpu.train.checkpoint import state_digest

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                         os.pardir))


@pytest.fixture(scope="module")
def criteo_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("criteo_pallas_gate")
    return generate_criteo_files(str(d), num_files=1, rows_per_file=600,
                                 vocab_per_slot=40, seed=21)


def _trainer_uniform(files, bs=200):
    desc = DataFeedDesc.criteo(batch_size=bs)
    desc.key_bucket_min = 512
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.set_thread(1)
    ds.load_into_memory()
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.05, mf_learning_rate=0.05)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 12, cfg=cfg,
                           unique_bucket_min=512)
    tr = Trainer(DeepFM(hidden=(16, 8)), table, desc, tx=optax.adam(1e-2),
                 seed=3)
    return tr, ds


def _ragged_records(n=400, num_slots=4, seed=0):
    """Zipf-ragged multi-key slots — the non-trivial segment stream that
    actually exercises the fused pooling kernel."""
    from paddlebox_tpu.data.record import SlotRecord
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        counts = np.minimum(rng.zipf(1.5, size=num_slots), 8)
        counts[rng.integers(0, num_slots)] = max(
            1, counts[rng.integers(0, num_slots)])
        offs = np.zeros(num_slots + 1, np.int32)
        np.cumsum(counts, out=offs[1:])
        keys = rng.integers(0, 3000, size=int(offs[-1])).astype(np.uint64)
        recs.append(SlotRecord(
            keys=keys, slot_offsets=offs,
            dense=rng.normal(size=3).astype(np.float32),
            label=float(i % 2), show=1.0, clk=float(i % 2)))
    return recs


def _trainer_ragged(bs=64, seed=0):
    from paddlebox_tpu.data import InMemoryDataset
    slots = [SlotDef("label", "float", 1), SlotDef("d", "float", 3)]
    slots += [SlotDef(f"S{i}", "uint64") for i in range(4)]
    desc = DataFeedDesc(slots=slots, label_slot="label", batch_size=bs,
                        key_bucket_min=512)
    ds = InMemoryDataset(desc)
    ds.records = _ragged_records(seed=seed)
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.05, mf_learning_rate=0.05)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 12, cfg=cfg,
                           unique_bucket_min=512)
    tr = Trainer(DeepFM(hidden=(16, 8)), table, desc, tx=optax.adam(1e-2),
                 seed=3)
    return tr, ds


def _logical_state(tr):
    """(sorted keys, host row blob, param leaves) — the numeric form of
    state_digest, comparable with a tolerance."""
    tr.sync_table()
    with tr.table.host_lock:
        keys, rows = tr.table.index.items()
    order = np.argsort(keys)
    blob = tr.table._gather_host(rows[order])
    leaves = [np.asarray(l) for l in jax.tree.leaves(
        jax.device_get(tr.state.params))]
    return keys[order], blob, leaves


def test_uniform_trivial_layout_digest_exact(criteo_files):
    """Trivial layout: the flag leaves the reshape fast path alone —
    the whole seeded train job is byte-for-byte identical."""
    with flags_scope(use_pallas_seqpool=False):
        tr0, ds = _trainer_uniform(criteo_files)
        tr0.train_pass(ds)
        d0 = state_digest(tr0)
    with flags_scope(use_pallas_seqpool=True):
        tr1, ds = _trainer_uniform(criteo_files)
        tr1.train_pass(ds)
        d1 = state_digest(tr1)
    assert d0 == d1


def test_pallas_gather_digest_exact(criteo_files):
    """use_pallas_gather=True (the already-wired table.py line-gather):
    gather_rows is bitwise a gather, so the digest matches exactly."""
    with flags_scope(use_pallas_gather=False):
        tr0, ds = _trainer_uniform(criteo_files)
        tr0.train_pass(ds)
        d0 = state_digest(tr0)
    with flags_scope(use_pallas_gather=True):
        tr1, ds = _trainer_uniform(criteo_files)
        tr1.train_pass(ds)
        d1 = state_digest(tr1)
    assert d0 == d1


def test_zipf_ragged_state_close(criteo_files):
    """Zipf-ragged resident train, two passes: fused Pallas pooling vs
    the XLA composition — same keys, table rows and dense params within
    the documented f32 tolerance (forward pooled outputs and the pushed
    grads both ride this: the table rows ARE the accumulated pushes)."""
    def run(flag):
        with flags_scope(use_pallas_seqpool=flag):
            tr, ds = _trainer_ragged()
            tr.train_pass(ds)
            tr.train_pass(ds)
            return _logical_state(tr)

    k0, b0, p0 = run(False)
    k1, b1, p1 = run(True)
    np.testing.assert_array_equal(k0, k1)
    for f in sorted(b0):
        np.testing.assert_allclose(
            b1[f], b0[f], rtol=2e-4, atol=2e-5,
            err_msg=f"table field {f} diverged beyond f32 tolerance")
    for a, b in zip(p0, p1):
        np.testing.assert_allclose(b, a, rtol=2e-4, atol=2e-5)


def _pv_train_state(flag_overrides, n_pvs=40, bs=32, seed=0):
    """Compact PV/AdsRank training job (the ISSUE 13 lane): PV-merged
    batches through rank_attention + slot_fc batch_fc + cross_norm,
    pull→train→push on a small table. Returns (params_leaves,
    table_packed) — the byte-comparable logical state."""
    import optax

    import jax.numpy as jnp

    from paddlebox_tpu.data import DataFeedDesc, SlotDef
    from paddlebox_tpu.data.pv import PvBatchBuilder
    from paddlebox_tpu.data.record import SlotRecord
    from paddlebox_tpu.models import AdsRank
    from paddlebox_tpu.ops import fused_seqpool_cvm, init_cross_norm_summary

    from paddlebox_tpu.ps import EmbeddingTable

    S, MR, DM = 4, 3, 8
    rng = np.random.default_rng(seed)
    recs = []
    for sid in range(n_pvs):
        n_ads = int(rng.integers(2, 4))
        ranks = rng.permutation(n_ads) + 1
        for a in range(n_ads):
            keys = (rng.integers(0, 60, S)
                    + np.arange(S) * 60).astype(np.uint64)
            label = float(rng.random() < 0.3)
            recs.append(SlotRecord(
                keys=keys, slot_offsets=np.arange(S + 1, dtype=np.int32),
                dense=rng.normal(size=2).astype(np.float32), label=label,
                show=1.0, clk=label, search_id=sid, rank=int(ranks[a]),
                cmatch=222))
    slots = [SlotDef("label", "float", 1), SlotDef("dense", "float", 2)]
    slots += [SlotDef(f"C{i}", "uint64") for i in range(S)]
    desc = DataFeedDesc(slots=slots, batch_size=bs, label_slot="label",
                        pv_batch_size=8, key_bucket_min=256)
    from paddlebox_tpu.ps import SparseSGDConfig
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.05, mf_learning_rate=0.05)
    with flags_scope(**flag_overrides):
        table = EmbeddingTable(mf_dim=4, capacity=1 << 10, cfg=cfg,
                               unique_bucket_min=256)
        model = AdsRank(d_model=DM, max_rank=MR, hidden=(8,),
                        slot_fc=True, cross_norm=True)
        summary = init_cross_norm_summary(1, DM)
        batches = PvBatchBuilder(desc, max_rank=MR).batches(recs)
        d = 3 + table.mf_dim
        b0, ro0 = batches[0]
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((bs, S, d)), jnp.zeros((bs, 2)),
                            jnp.asarray(ro0), summary)
        import optax as _optax
        tx = _optax.adam(5e-3)
        opt = tx.init(params)

        @jax.jit
        def step(params, opt, values_k, segments, show_clk, dense,
                 label, ro):
            def loss_fn(params, values_k):
                pooled = fused_seqpool_cvm(values_k, segments, show_clk,
                                           bs, S)
                logits = model.apply(params, pooled, dense, ro, summary)
                return jnp.mean(
                    _optax.sigmoid_binary_cross_entropy(logits, label))
            _, (gp, gk) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(params, values_k)
            upd, opt = tx.update(gp, opt, params)
            return _optax.apply_updates(params, upd), opt, gk

        for batch, ro in batches:
            idx = table.prepare(batch)
            values_k = table.pull(idx)
            show_clk = jnp.stack([jnp.asarray(batch.show),
                                  jnp.asarray(batch.clk)], axis=1)
            params, opt, gk = step(
                params, opt, values_k, jnp.asarray(batch.segments),
                show_clk, jnp.asarray(batch.dense),
                jnp.asarray(batch.label), jnp.asarray(ro))
            table.push(idx, gk)
        leaves = [np.asarray(l) for l in jax.tree.leaves(
            jax.device_get(params))]
        packed = np.asarray(table.state.packed)
    return leaves, packed


def test_pv_train_default_off_byte_identical():
    """The ISSUE 13 acceptance digest gate, PV half: a seeded PV/
    AdsRank train job under DEFAULT flags is byte-for-byte identical to
    one with the three CTR flags explicitly off (defaults really are
    off and the seams leave the program untouched)."""
    l0, p0 = _pv_train_state({})
    l1, p1 = _pv_train_state(dict(use_pallas_rank_attention=False,
                                  use_pallas_batch_fc=False,
                                  use_pallas_cross_norm=False))
    assert p0.tobytes() == p1.tobytes()
    for a, b in zip(l0, l1):
        assert a.tobytes() == b.tobytes()


def test_pv_train_pallas_state_close():
    """Flag-on PV train vs the XLA composition: rank_attention/batch_fc
    grads are bitwise, so the only drift is the fused forwards' MXU
    summation order compounding through Adam — the same f32 tolerance
    class as the zipf seqpool gate."""
    l0, p0 = _pv_train_state({})
    l1, p1 = _pv_train_state(dict(use_pallas_rank_attention=True,
                                  use_pallas_batch_fc=True,
                                  use_pallas_cross_norm=True))
    np.testing.assert_allclose(p1, p0, rtol=2e-4, atol=2e-5)
    for a, b in zip(l0, l1):
        np.testing.assert_allclose(b, a, rtol=2e-3, atol=2e-4)


def test_resident_digest_immune_to_ctr_flags(criteo_files):
    """The ISSUE 13 acceptance digest gate, resident half: the CTR op
    family is not on the DeepFM resident path, so flipping all three
    flags ON must reproduce the flag-off resident state_digest EXACTLY
    (no accidental coupling through shared modules)."""
    with flags_scope(use_pallas_rank_attention=False,
                     use_pallas_batch_fc=False,
                     use_pallas_cross_norm=False):
        tr0, ds = _trainer_uniform(criteo_files)
        tr0.train_pass(ds)
        d0 = state_digest(tr0)
    with flags_scope(use_pallas_rank_attention=True,
                     use_pallas_batch_fc=True,
                     use_pallas_cross_norm=True):
        tr1, ds = _trainer_uniform(criteo_files)
        tr1.train_pass(ds)
        d1 = state_digest(tr1)
    assert d0 == d1


# ---- ISSUE 19: device-resident key index (use_pallas_index) ------------

def test_index_depth2_preloader_digest_matches_flag_off(criteo_files):
    """The ISSUE 19 acceptance digest gate, resident half: a depth-2
    preloaded multi-pass run with use_pallas_index=1 (device dedup +
    hash-insert row assignment, host kv mirrored with new keys only)
    reproduces the depth-0 flag-off state_digest EXACTLY."""
    with flags_scope(use_pallas_index=False):
        tr0, ds = _trainer_uniform(criteo_files)
        tr0.train_passes_resident([ds] * 4, depth=0)
        d0 = state_digest(tr0)
    with flags_scope(use_pallas_index=True):
        tr1, ds = _trainer_uniform(criteo_files)
        tr1.train_passes_resident([ds] * 4, depth=2)
        d1 = state_digest(tr1)
    assert d0 == d1
    # the device route actually served (not a silent host fallback)
    dev = tr1.table._dev_index
    assert dev is not None and not dev.degraded, dev and dev.degrade_reason


def test_index_sharded_digest_matches_flag_off(criteo_files):
    """The ISSUE 19 acceptance digest gate, sharded half: streaming +
    resident passes on a 2-device mesh with use_pallas_index=1 (per-
    shard device mirrors behind _shard_rows) reproduce the flag-off
    sharded_state_digest EXACTLY."""
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
    from paddlebox_tpu.train.checkpoint import sharded_state_digest
    from paddlebox_tpu.train.sharded import ShardedTrainer
    mesh = make_mesh(2)
    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 512
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(criteo_files)
    ds.load_into_memory()

    def run(flag):
        cfg = SparseSGDConfig(mf_create_thresholds=0.0,
                              mf_initial_range=0.0,
                              learning_rate=0.1, mf_learning_rate=0.1)
        table = ShardedEmbeddingTable(2, mf_dim=4,
                                      capacity_per_shard=4096, cfg=cfg,
                                      req_bucket_min=256,
                                      serve_bucket_min=256)
        with flags_scope(use_pallas_index=flag,
                         log_period_steps=10 ** 6):
            tr = ShardedTrainer(DeepFM(hidden=(16, 16)), table, desc,
                                mesh, tx=optax.adam(2e-3))
            tr.train_pass(ds)
            tr.train_pass_resident(ds)
        return sharded_state_digest(tr)

    assert run(True) == run(False)


def test_index_overflow_degrades_without_digest_drift(criteo_files):
    """Capacity/probe-pressure overflow mid-run flips the mirror to the
    host path LOUDLY (warning + index.assign/host booked) and the final
    state_digest still matches flag-off exactly — degraded never means
    wrong."""
    import logging
    from paddlebox_tpu.obs import MemorySink
    from paddlebox_tpu.obs.hub import get_hub, reset_hub
    from paddlebox_tpu.ops.pallas_index import DeviceKeyIndex
    with flags_scope(use_pallas_index=False):
        tr0, ds = _trainer_uniform(criteo_files)
        tr0.train_passes_resident([ds] * 2, depth=0)
        d0 = state_digest(tr0)
    reset_hub()
    hub = get_hub()
    hub.add_sink(MemorySink())
    records = []
    handler = logging.Handler()
    handler.emit = records.append
    logging.getLogger("paddlebox_tpu").addHandler(handler)
    try:
        with flags_scope(use_pallas_index=True):
            tr1, ds = _trainer_uniform(criteo_files)
            # plant a crippled mirror: 512 buckets cannot hold criteo's
            # ~1k pass uniques -> probe overflow on the first bulk
            # assign, sticky degrade, host path from then on
            tr1.table._dev_index = DeviceKeyIndex(tr1.table.capacity,
                                                  n_buckets=512)
            tr1.train_passes_resident([ds] * 2, depth=2)
            d1 = state_digest(tr1)
        c = hub.counter("pbox_kernel_dispatch_total")
        assert c.value(kernel="index.assign", impl="host") >= 1
    finally:
        logging.getLogger("paddlebox_tpu").removeHandler(handler)
        reset_hub()
    assert d1 == d0
    dev = tr1.table._dev_index
    assert dev.degraded and "overflow" in dev.degrade_reason
    assert any("degraded" in r.getMessage() for r in records), \
        "overflow degrade was silent — must warn"


def test_index_abort_polled_build_rolls_back(criteo_files):
    """A stop-polled (aborted) flag-on preloader build leaves the host
    kv authoritative and the device mirror either exactly in sync or
    degraded — and the pipeline restarts cleanly after clear_stop."""
    from paddlebox_tpu.resilience import preemption
    from paddlebox_tpu.train.device_pass import PassPreloader
    with flags_scope(use_pallas_index=True):
        tr, ds = _trainer_uniform(criteo_files)
        pre = PassPreloader(iter([ds] * 6), tr.table, depth=1)
        try:
            pre.start_next()
            assert pre.wait() is not None
            preemption.request_stop("test")
            while pre.wait() is not None:   # drain staged passes
                pass
            pre.drain(timeout=30)
        finally:
            preemption.clear_stop()
            pre.drain()
        dev = tr.table._dev_index
        if dev is not None and not dev.degraded:
            with tr.table.host_lock:
                keys, rows = tr.table.index.items()
            assert len(keys) == dev.next_row
            np.testing.assert_array_equal(dev.lookup_rows(keys),
                                          rows.astype(np.int64))
        # aborted build rolled back cleanly: a fresh flag-on run from
        # this table still digests identically to flag-off from scratch
        tr.train_passes_resident([ds], depth=1)
    with flags_scope(use_pallas_index=False):
        tr0, ds0 = _trainer_uniform(criteo_files)
        tr0.train_passes_resident([ds0], depth=0)
    assert state_digest(tr) == state_digest(tr0)


def test_committed_kernel_trajectory_gates():
    """The interpret-mode CPU kernel round is recorded (satellite:
    kernel.* rows live in BENCH_trajectory.json) and the perf gate
    passes over it."""
    import importlib.util
    path = os.path.join(REPO_ROOT, "BENCH_trajectory.json")
    with open(path) as fh:
        data = json.load(fh)
    metrics = {r["metric"] for r in data["rows"]}
    for probe in ("gather", "pool_cvm", "fused",
                  # the ISSUE 13 CTR family round (KERNELS_r02)
                  "rank_attention", "batch_fc", "cross_norm",
                  # the ISSUE 19 device key-index round (KERNELS_r03)
                  "index.insert", "index.lookup", "index.dedup"):
        assert any(m.startswith(f"kernel.{probe}.") and m.endswith(".cpu")
                   for m in metrics), f"no recorded kernel.{probe}.* row"
    # the PV rank-attention bench lane's rows (BENCH_MODE=pv) are
    # folded and gated alongside the kernel rounds
    assert "adsrank_pv_examples_per_sec_per_chip" in metrics
    assert "adsrank_pv_examples_per_sec_per_chip_pallas" in metrics
    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(REPO_ROOT, "scripts", "perf_gate.py"))
    pg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pg)
    assert pg.check(path, ignore_live=True) == 0
