#!/usr/bin/env python
"""Benchmark: DeepFM CTR training throughput on one chip.

Prints one JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline derivation (BASELINE.md): north-star is 1M examples/sec on a
v5p-32 slice (16 chips) ⇒ 62,500 examples/sec/chip. vs_baseline is
measured chip throughput / 62,500.

The measured pass mirrors the reference's steady state (SURVEY.md §3.2):
data already resident in memory (loaded during the previous pass window),
per-batch host prep (dedup + row assign) overlapped with device compute via
the prefetch thread, one fused jit step per batch.
"""

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def build_records(num_records: int, num_slots: int = 26,
                  vocab_per_slot: int = 100_000, seed: int = 0,
                  avg_keys_per_slot: float = 1.0,
                  key_dist: str = "uniform"):
    """Synthetic criteo-shaped records, built columnar-fast.

    ``avg_keys_per_slot > 1`` produces RAGGED slots: per-(record, slot)
    key counts ~ 1 + Poisson(avg-1) — variable-length multi-key slots,
    the real PaddleBox feed-log shape (data_feed.h:2066-2287) that
    stresses the segment stream and the non-trivial seqpool path.

    ``key_dist="zipf"`` draws per-slot key ids from a bounded Zipf
    (s=1.2) instead of uniform — the hot-key CTR shape
    (docs/BENCH_SHAPES.md): a few ids dominate every batch, so dedup,
    the persistent HBM window and the host/SSD tiers stop being
    flattered by uniform draws (ROADMAP item 5)."""
    from paddlebox_tpu.data.record import SlotRecord
    rng = np.random.default_rng(seed)

    def draw_keys(size):
        if key_dist == "zipf":
            # bounded Zipf over [0, vocab): P(r) ∝ 1/(r+1)^1.2 — one
            # vectorized choice() call per pass build
            w = 1.0 / np.arange(1, vocab_per_slot + 1,
                                dtype=np.float64) ** 1.2
            return rng.choice(vocab_per_slot, size=size, p=w / w.sum())
        return rng.integers(0, vocab_per_slot, size=size)

    dense_all = rng.normal(size=(num_records, 13)).astype(np.float32)
    labels = (rng.random(num_records) < 0.25).astype(np.float32)
    slot_base = (np.arange(num_slots) * vocab_per_slot).astype(np.uint64)
    if avg_keys_per_slot <= 1.0:
        keys_all = draw_keys((num_records, num_slots))
        keys_all = (keys_all + slot_base).astype(np.uint64)
        offsets = np.arange(num_slots + 1, dtype=np.int32)
        return [
            SlotRecord(keys=keys_all[i], slot_offsets=offsets,
                       dense=dense_all[i], label=float(labels[i]),
                       show=1.0, clk=float(labels[i]))
            for i in range(num_records)
        ]
    counts = 1 + rng.poisson(avg_keys_per_slot - 1.0,
                             size=(num_records, num_slots))
    offs = np.zeros((num_records, num_slots + 1), np.int32)
    np.cumsum(counts, axis=1, out=offs[:, 1:])
    total = offs[:, -1]
    flat = draw_keys(int(total.sum()))
    flat_base = np.repeat(
        np.tile(slot_base, num_records),
        counts.reshape(-1))
    flat = (flat + flat_base).astype(np.uint64)
    starts = np.concatenate([[0], np.cumsum(total)[:-1]])
    return [
        SlotRecord(keys=flat[starts[i]:starts[i] + total[i]],
                   slot_offsets=offs[i],
                   dense=dense_all[i], label=float(labels[i]),
                   show=1.0, clk=float(labels[i]))
        for i in range(num_records)
    ]


def dense_flops_per_example(params) -> float:
    """Analytic train-step FLOPs/example of the DENSE net: 2·in·out per
    matmul kernel forward, ×3 for fwd+bwd (the embedding path is
    bandwidth-bound — gathers/scatters, ~0 FLOPs). Used for the MFU
    line; the denominator is the chip's matmul peak."""
    import jax
    f = 0.0
    for leaf in jax.tree.leaves(params):
        if getattr(leaf, "ndim", 0) >= 2:
            f += 2.0 * float(np.prod(leaf.shape))
    return 3.0 * f


SHAPES = {
    # BENCH_SHAPE → (num_slots, avg_keys_per_slot, default_bs,
    #                default_records, default_vocab_per_slot, key_dist)
    "uniform": (26, 1.0, 8192, 262_144, 100_000, "uniform"),
    "ragged": (26, 5.0, 4096, 131_072, 100_000, "uniform"),
    "thousand": (1000, 1.0, 512, 32_768, 4_000, "uniform"),
    # hot-key CTR shape (ROADMAP item 5; docs/BENCH_SHAPES.md): bounded
    # Zipf key draws — same geometry as "uniform" so the two rows
    # isolate the skew effect on dedup / window / tier hit rates
    "zipf": (26, 1.0, 8192, 262_144, 100_000, "zipf"),
}


def measure_tiered(num_passes: int = 4, shape: str = "uniform") -> dict:
    """Pass-window benchmark: the tiered sharded PS with PERSISTENT HBM
    windows (ps/tiered.py), driven through the UNIFIED pass pipeline
    (train/device_pass.PassPipeline — ISSUE 9): plan build, dedup/pack,
    the H2D wire and the host-tier feed-pass fetch all ride the depth-N
    preloader worker, begin_pass is reconcile-only, end_pass submits to
    the epilogue lane (which also evicts ahead for the next queued
    stage). Consecutive passes draw from the same key space (the CTR
    workload), so delta staging shrinks the begin boundary to ~the
    working-set delta; a drop_window control pass measures what full
    re-staging would cost on the same box state. Returns the JSON
    record (caller prints)."""
    import jax
    import optax

    from paddlebox_tpu.config import FLAGS
    from paddlebox_tpu.data import DataFeedDesc, InMemoryDataset, SlotDef
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.ps import BoxPSHelper, SparseSGDConfig
    from paddlebox_tpu.ps.tiered import TieredShardedEmbeddingTable
    from paddlebox_tpu.train.sharded import ShardedTrainer

    n_slots, avg_keys, bs_default, _, _, key_dist = SHAPES[shape]
    bs = int(os.environ.get("BENCH_BATCH_SIZE", bs_default))
    # smaller working set than the resident headline: the cold stage
    # ships the full working set over the tunnel once
    num_records = int(os.environ.get("BENCH_RECORDS", 32768))
    vocab = int(os.environ.get("BENCH_VOCAB", 10_000))
    mf_dim = int(os.environ.get("BENCH_MF_DIM", 8))
    chips = len(jax.devices())
    slots = [SlotDef("label", "float", 1), SlotDef("dense", "float", 13)]
    slots += [SlotDef(f"C{i}", "uint64") for i in range(1, n_slots + 1)]
    desc = DataFeedDesc(slots=slots, batch_size=bs, label_slot="label",
                        key_bucket_min=(bs * n_slots
                                        if avg_keys <= 1.0 else 4096))
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3)

    def make_ds(seed: int) -> InMemoryDataset:
        d = InMemoryDataset(desc)
        d.records = build_records(num_records, num_slots=n_slots,
                                  vocab_per_slot=vocab, seed=seed,
                                  avg_keys_per_slot=avg_keys,
                                  key_dist=key_dist)
        d.columnarize()
        return d

    mesh = make_mesh(chips)
    # SSD third tier attached (ps/ssd.py): idle during the headline
    # passes (occupancy 0 below the demote watermark), then exercised
    # by the promote-attribution section below
    ssd_root = tempfile.mkdtemp(prefix="pbox_bench_ssd_")
    table = TieredShardedEmbeddingTable(
        chips, mf_dim=mf_dim, capacity_per_shard=(1 << 22) // chips,
        cfg=cfg, req_bucket_min=1 << 12, serve_bucket_min=1 << 12,
        ssd_dir=ssd_root)
    tr = ShardedTrainer(DeepFM(hidden=(512, 256, 128)), table,
                        desc, mesh, tx=optax.adam(1e-3))
    helper = BoxPSHelper(table, trainer=tr)
    pool = [make_ds(s) for s in range(2)]

    # the pipeline: cold pass + measured passes, alternating datasets
    # (~96% key overlap). BENCH_NO_OVERLAP=1 = the sequential
    # kick-per-pass control (depth 0); BENCH_PRELOAD_DEPTH overrides.
    no_overlap = os.environ.get("BENCH_NO_OVERLAP", "0") == "1"
    depth = (0 if no_overlap else
             int(os.environ.get("BENCH_PRELOAD_DEPTH",
                                str(FLAGS.preload_depth))))
    seq = [pool[i % 2] for i in range(num_passes + 2)]
    pipe = tr.tiered_pass_pipeline(iter(seq), depth=depth)
    pipe.start_next()

    def one_pass():
        t0 = time.perf_counter()
        rp = pipe.wait()
        t_wait = time.perf_counter() - t0     # prologue stall
        t1 = time.perf_counter()
        pipe.begin_pass()                     # reconcile-only boundary
        t_begin = time.perf_counter() - t1
        if not no_overlap:
            pipe.start_next()
        t2 = time.perf_counter()
        tr.train_pass_resident(rp)
        t_train = time.perf_counter() - t2
        if no_overlap:
            pipe.start_next()
        t3 = time.perf_counter()
        pipe.end_pass()
        # with the async epilogue (FLAGS.async_end_pass, the default)
        # this is SUBMIT time — the HBM→host write-back drains in the
        # background; its true cost/overlap comes from endpass_stats()
        t_end = time.perf_counter() - t3
        return t_wait, t_begin, t_train, t_end, \
            dict(table.last_pass_stats)

    # warmup, the resident headline's discipline (its pass 0 pays
    # compile+upload and is excluded): TWO unmeasured passes — the cold
    # pass stages the full working set + compiles dataset A's shapes,
    # the warm pass stages the A→B key delta + compiles B's shapes (the
    # two datasets' routing buckets can differ, each costing a one-off
    # jit). Pass 1's build+stage already ride the worker during cold
    # training (the pre_build_thread shape, ps_gpu_wrapper.cc:913);
    # measured passes then show the steady-state boundary.
    w0, b0, _, e0, st0 = one_pass()
    w1, b1, _, _, st1 = one_pass()
    # scope the epilogue accounting to the MEASURED passes: drain the
    # warmup passes' write-backs and snapshot the cumulative stats; the
    # post-loop snapshot diffs against this (the warmups and the
    # device-only rerun below would otherwise pollute the headline
    # overlap fraction)
    table.fence()
    eps0 = table.endpass_stats()
    wait_l, begin_l, train_l, end_l = [], [], [], []
    staged_l, stall_l, ep_dispatch_l = [], [], []
    for i in range(num_passes):
        w, b, t, e, st = one_pass()
        wait_l.append(w)
        begin_l.append(w + b)   # critical-path boundary stall: preload
        train_l.append(t)       # wait + the reconcile-only begin
        end_l.append(e)
        staged_l.append(st["staged"])
        ep_dispatch_l.append(st.get("end_pass_dispatch_sec", 0.0))
        # per-pass begin_stall attribution (ps/tiered.begin_pass):
        # stage wait on the critical path, evict+scatter, the async-
        # lane vs emergency-inline eviction split, and the SSD promote
        # seconds the staging incurred (wait = main-thread share — ~0
        # when the promote rode the overlapped stage)
        stall_l.append({k: st.get(k, 0.0)
                        for k in ("stage_wait_sec", "evict_scatter_sec",
                                  "evict_async_sec", "evict_async_rows",
                                  "evict_emergency_sec",
                                  "ssd_promote_sec",
                                  "ssd_promote_wait_sec",
                                  "ssd_promoted_rows")})
    # drain the measured passes' epilogue, then diff the cumulative
    # accounting against the cold-pass snapshot — end_pass_overlap_sec
    # is the measured write-back time that never blocked the main
    # thread (the seconds the async epilogue bought). The fence here is
    # part of the accounting: the LAST measured pass's write-back has
    # no next pass to hide behind in this loop, so any residual wait
    # honestly lands in the critical fence-wait term.
    table.fence()
    eps1 = table.endpass_stats()
    eps = {k: eps1[k] - eps0[k] for k in
           ("jobs_run", "writeback_sec", "fence_wait_sec",
            "critical_fence_wait_sec")}
    eps["overlap_sec"] = max(
        0.0, eps["writeback_sec"] - eps["critical_fence_wait_sec"])
    pipe_stats = dict(
        preload_depth=depth,
        preload_builds=pipe.builds,
        preload_build_sec_total=round(pipe.build_sec_total, 4),
        preload_build_stage_sec={
            k: round(v, 4)
            for k, v in sorted(pipe.build_stage_sec.items())})
    # quiesce the pipeline before the reruns/controls: stop the worker
    # and discard queued stages that will never begin (their plan pins
    # release — ps/tiered.discard_queued_stages)
    pipe.drain()
    # device-only rerun (duty-cycle attribution): re-stage the last
    # pass classically, build once, and re-train the staged batches —
    # nothing rides the tunnel, so this is the device's real compute
    # time per pass (same two-rerun discipline as the resident
    # headline; these extra passes perturb only model state, which the
    # tiered bench does not report, and run AFTER the epilogue
    # accounting snapshot so they cannot skew it)
    ds_dev = pool[(num_passes + 1) % 2]
    helper.begin_pass(ds_dev)
    rp_dev = tr.build_resident_pass(ds_dev)
    tr.train_pass_resident(rp_dev)          # warm rerun
    t0 = time.perf_counter()
    tr.train_pass_resident(rp_dev)
    dev_only = num_records / max(time.perf_counter() - t0, 1e-9)
    helper.end_pass(None)
    # control: drop residency, re-stage the SAME working set as the
    # last measured pass, fully (drop_window also discards the stage
    # the last pass overlapped)
    table.drop_window()
    t0 = time.perf_counter()
    helper.begin_pass(pool[(num_passes + 1) % 2])
    begin_full = time.perf_counter() - t0
    staged_full = table.last_pass_stats["staged"]
    helper.end_pass(None)
    # --- SSD third-tier attribution (ISSUE 7; docs/STORAGE.md) ---
    # Demote the WHOLE model to segments, then stage pass B's working
    # set back twice: once synchronously (begin_pass pays the segment
    # reads inline — the LoadSSD2Mem cost on the critical path) and
    # once ridden on the overlapped stage during pass A's training
    # (the production pre_build_thread shape). The acceptance claim is
    # overlap_promote_wait_sec << sync_promote_wait_sec for the same
    # working set (scripts/ssd_check.run_overlap_check gates it; the
    # bench reports the measured numbers).
    table.fence()
    table.drop_window()
    t0 = time.perf_counter()
    ssd_demoted = sum(h.demote_cold() for h in table.hosts)
    ssd_demote_sec = time.perf_counter() - t0
    t0 = time.perf_counter()
    helper.begin_pass(pool[1])            # sync: promote paid inline
    begin_ssd_sync = time.perf_counter() - t0
    sync_st = dict(table.last_pass_stats)
    helper.end_pass(None)
    table.fence()
    table.drop_window()
    sum(h.demote_cold() for h in table.hosts)
    helper.begin_pass(pool[0])            # A staged inline (unmeasured)
    helper.stage_pass(pool[1])            # B's promote rides A's train
    tr.train_pass_resident(pool[0])
    helper.end_pass(pool[0])
    t0 = time.perf_counter()
    helper.begin_pass(pool[1])
    begin_ssd_overlap = time.perf_counter() - t0
    ov_st = dict(table.last_pass_stats)
    helper.end_pass(None)
    table.fence()
    ssd = table.ssd_stats()
    shutil.rmtree(ssd_root, ignore_errors=True)
    walls = [b + t + e for b, t, e in zip(begin_l, train_l, end_l)]
    value = num_records * len(walls) / sum(walls) / chips
    dev_time_total = num_records * len(walls) / max(dev_only, 1e-9)
    # steady state = the median begin (the first delta pass pays any
    # residual compile; later passes show the true boundary)
    begin_steady = float(np.median(begin_l))
    metric = "deepfm_ctr_examples_per_sec_per_chip"
    if shape != "uniform":
        metric += f"_{shape}"
    return {
        "metric": metric + "_tiered",
        "value": round(value, 1),
        "unit": "examples/sec/chip",
        "vs_baseline": round(value / (1_000_000 / 16), 4),
        "mode": "tiered", "shape": shape, "batch_size": bs,
        "num_slots": n_slots, "avg_keys_per_slot": avg_keys,
        "records_per_pass": num_records,
        "passes": num_passes,
        "stage_cold_sec": round(w0 + b0, 3),
        "staged_rows_cold": st0["staged"],
        # begin_delta = the critical-path pass boundary: preload wait
        # (build+stage pipeline starvation) + the reconcile-only begin
        "begin_delta_sec": [round(b, 3) for b in begin_l],
        "preload_wait_sec": [round(w, 3) for w in wait_l],
        "staged_rows_delta": staged_l,
        "train_sec": [round(t, 3) for t in train_l],
        # unified pass pipeline (train/device_pass.PassPipeline):
        # depth + worker build accounting, the resident bench's fields
        **pipe_stats,
        # async epilogue: end_pass_sec is SUBMIT time (critical-path
        # cost of the boundary); the write-back itself runs overlapped.
        # dispatch = the bucketed D2H gather dispatch inside submit
        # (the rest is the touched-row snapshot) — the submit-parity
        # audit's split (ISSUE 9)
        "end_pass_sec": [round(e, 3) for e in end_l],
        "end_pass_dispatch_sec": [round(d, 4) for d in ep_dispatch_l],
        "end_pass_writeback_sec_total": round(eps["writeback_sec"], 4),
        "end_pass_fence_wait_sec_total": round(
            eps["critical_fence_wait_sec"], 4),
        # the headline of ISSUE 4: write-back seconds off the critical
        # path, and their fraction of total write-back time (>0.5 =
        # the epilogue is genuinely overlapped with next-pass train)
        "end_pass_overlap_sec": round(eps["overlap_sec"], 4),
        "end_pass_overlap_frac": round(
            eps["overlap_sec"] / max(eps["writeback_sec"], 1e-9), 4),
        "end_pass_jobs": eps["jobs_run"],
        # fraction of measured wall the device spent on real compute
        # (records/dev_only per pass, wire-free rerun — the resident
        # headline's device_busy_frac, now for tiered mode)
        "device_busy_frac": round(
            min(dev_time_total / max(sum(walls), 1e-9), 1.0), 4),
        "device_only_ex_per_sec": round(dev_only / chips, 1),
        "begin_delta_steady_sec": round(begin_steady, 4),
        # the first DELTA boundary is the warm (2nd unmeasured) pass:
        # it stages the A→B working-set delta + pays B's one-off compile
        "begin_first_delta_sec": round(w1 + b1, 3),
        "staged_rows_first_delta": st1["staged"],
        "begin_full_control_sec": round(begin_full, 3),
        "staged_rows_full_control": staged_full,
        # the headline ratio: steady-state boundary stall with delta
        # staging vs full re-staging of the same working set
        "begin_stall_shrink": round(
            begin_full / max(begin_steady, 1e-9), 1),
        # per-pass begin_stall attribution (stage wait / evict+scatter /
        # SSD promote seconds) — the tiered-mode gap finally has
        # per-stage numbers (ISSUE 7)
        "begin_stall_breakdown": [
            {k: (round(float(v), 4) if isinstance(v, float) else v)
             for k, v in st.items()} for st in stall_l],
        # SSD third tier (ps/ssd.py): cumulative tier accounting plus
        # the sync-vs-overlapped promote comparison for pass B's
        # working set — overlap wait must sit far below the sync
        # control where begin_pass pays the segment reads inline
        "ssd": {
            "demoted_rows": int(ssd.get("demoted_rows", 0)),
            "promoted_rows": int(ssd.get("promoted_rows", 0)),
            "compacted_rows": int(ssd.get("compacted_rows", 0)),
            "demote_sec_total": round(ssd.get("demote_sec", 0.0), 4),
            "promote_sec_total": round(ssd.get("promote_sec", 0.0), 4),
            "promote_wait_sec_total": round(
                ssd.get("promote_wait_sec", 0.0), 4),
            "live_rows": int(ssd.get("live_rows", 0)),
            "segments": int(ssd.get("segments", 0)),
            "bytes": int(ssd.get("bytes", 0)),
            "demote_all_rows": int(ssd_demoted),
            "demote_all_sec": round(ssd_demote_sec, 4),
            "begin_sync_sec": round(begin_ssd_sync, 4),
            "begin_overlap_sec": round(begin_ssd_overlap, 4),
            "sync_promote_wait_sec": round(
                sync_st.get("ssd_promote_wait_sec", 0.0), 4),
            "sync_promoted_rows": int(
                sync_st.get("ssd_promoted_rows", 0)),
            "overlap_promote_sec": round(
                ov_st.get("ssd_promote_sec", 0.0), 4),
            "overlap_promote_wait_sec": round(
                ov_st.get("ssd_promote_wait_sec", 0.0), 4),
            "overlap_promoted_rows": int(
                ov_st.get("ssd_promoted_rows", 0)),
        },
    }


def measure_multichip(shape: str = "uniform") -> None:
    """BENCH_MODE=multichip (ISSUE 11): one subprocess per chip count N
    (``XLA_FLAGS=--xla_force_host_platform_device_count=N`` on the CPU
    backend; on real hardware point it at slices instead), each running
    the SHARDED bench at a fixed small per-chip workload, then emit

        sharded.n{N}.{shape}.ex_per_sec_per_chip
        sharded.n{N}.{shape}.scaling_efficiency   (vs the smallest N)

    rows through emit_result — so they fold into BENCH_trajectory.json
    and ``scripts/perf_gate.py --check`` guards multichip scaling the
    same way it guards the resident bench. CPU-mesh numbers are
    recorded as what they are (virtual devices share one socket, so
    efficiency ≈ 1/N there); the gate compares each key ACROSS ROUNDS,
    never across N. BENCH_A2A_CHUNKS sets FLAGS_a2a_chunks in the
    children to measure the chunked schedule's scaling."""
    import subprocess
    ns = [int(x) for x in os.environ.get("BENCH_MULTICHIP_NS",
                                         "1,2,4,8").split(",")]
    bs = int(os.environ.get("BENCH_MULTICHIP_BS", "1024"))
    gbatches = int(os.environ.get("BENCH_MULTICHIP_BATCHES", "3"))
    passes = int(os.environ.get("BENCH_MULTICHIP_PASSES", "2"))
    timeout_s = float(os.environ.get("BENCH_MULTICHIP_TIMEOUT", "600"))
    chunks = os.environ.get("BENCH_A2A_CHUNKS", "")
    here = os.path.dirname(os.path.abspath(__file__))
    per_chip = {}
    meta = {}
    for n in ns:
        env = dict(os.environ)
        xf = [f for f in env.get("XLA_FLAGS", "").split()
              if "xla_force_host_platform_device_count" not in f]
        env["XLA_FLAGS"] = " ".join(
            xf + [f"--xla_force_host_platform_device_count={n}"])
        env.update(
            JAX_PLATFORMS="cpu", BENCH_MODE="sharded", BENCH_SHAPE=shape,
            BENCH_BATCH_SIZE=str(bs),
            BENCH_RECORDS=str(bs * n * gbatches),
            BENCH_PASSES=str(passes), BENCH_MAX_PASSES=str(passes),
            BENCH_WALL_BUDGET_SEC="120", BENCH_XPLANE="0",
            BENCH_TIERED_ROW="0", BENCH_TRAJECTORY="0",
            BENCH_TELEMETRY_JSONL="0",
            # the children measure throughput; the exchange probe runs
            # once, chunk-aware, only when a chunk sweep is requested
            BENCH_A2A_PROBE="1" if chunks else "0")
        if chunks:
            env["FLAGS_a2a_chunks"] = chunks
        t0 = time.perf_counter()
        try:
            cp = subprocess.run(
                [sys.executable, os.path.join(here, "bench.py")],
                env=env, capture_output=True, text=True,
                timeout=timeout_s)
        except subprocess.TimeoutExpired:
            print(f"multichip n={n}: timed out after {timeout_s:.0f}s",
                  file=sys.stderr)
            continue
        row = None
        for line in reversed(cp.stdout.splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "metric" in d and isinstance(d.get("value"), (int, float)):
                row = d
                break
        if cp.returncode != 0 or row is None:
            print(f"multichip n={n}: bench failed rc={cp.returncode}: "
                  f"{cp.stderr[-500:]}", file=sys.stderr)
            continue
        per_chip[n] = float(row["value"])
        meta[n] = dict(wall_sec=round(time.perf_counter() - t0, 1),
                       records_per_pass=bs * n * gbatches)
    if not per_chip:
        print("multichip: no chip count produced a row", file=sys.stderr)
        sys.exit(1)
    # efficiency is DEFINED against the smallest REQUESTED N: if that
    # child failed, emitting ratios against a shifted baseline would
    # poison the key's gate history (a later healthy round's honest
    # n/base ratio reads as a spurious regression) — skip them instead
    base_n = min(ns)
    base = per_chip.get(base_n)
    if base is None:
        print(f"multichip: baseline n={base_n} failed — emitting "
              "per-chip rows only, no scaling_efficiency this round",
              file=sys.stderr)
    # a chunked-schedule ladder gates under its OWN keys (…{shape}.c{c}.…):
    # perf_gate keys on the metric name, and comparing a chunks=2 round
    # against a chunks=1 best would gate incompatible schedules
    shape_key = shape if int(chunks or 1) <= 1 else f"{shape}.c{chunks}"
    for n in sorted(per_chip):
        common = {"mode": "multichip", "shape": shape, "n_chips": n,
                  "batch_size": bs, "a2a_chunks": int(chunks or 1),
                  **meta[n]}
        emit_result({
            "metric": f"sharded.n{n}.{shape_key}.ex_per_sec_per_chip",
            "value": round(per_chip[n], 1),
            "unit": "examples/sec/chip",
            "vs_baseline": round(per_chip[n] / (1_000_000 / 16), 4),
            **common})
        if base is not None:
            emit_result({
                "metric": f"sharded.n{n}.{shape_key}.scaling_efficiency",
                "value": round(per_chip[n] / base, 4),
                "unit": f"frac of n{base_n} per-chip rate",
                "vs_baseline": None, **common})


def build_pv_records(n_pvs: int, num_slots: int, vocab_per_slot: int,
                     dense_dim: int, seed: int = 0):
    """Synthetic search pages for the PV rank-attention lane: 2-4 ads
    per PV with shuffled 1-based ranks and valid cmatch, so every batch
    carries a dense rank_offset matrix (data/pv.build_rank_offset)."""
    from paddlebox_tpu.data.record import SlotRecord
    rng = np.random.default_rng(seed)
    recs = []
    for sid in range(n_pvs):
        n_ads = int(rng.integers(2, 5))
        ranks = rng.permutation(n_ads) + 1
        for a in range(n_ads):
            keys = (rng.integers(0, vocab_per_slot, num_slots)
                    + np.arange(num_slots) * vocab_per_slot).astype(
                        np.uint64)
            label = float(rng.random() < 0.25)
            recs.append(SlotRecord(
                keys=keys,
                slot_offsets=np.arange(num_slots + 1, dtype=np.int32),
                dense=rng.normal(size=dense_dim).astype(np.float32),
                label=label, show=1.0, clk=label, search_id=sid,
                rank=int(ranks[a]), cmatch=222))
    return recs


def measure_pv(num_passes: int = 3) -> list:
    """BENCH_MODE=pv (ISSUE 13 / ROADMAP item 5): the PV-batch
    rank-attention scenario — PvBatchBuilder batches (PV merge +
    rank_offset) through an AdsRank net with ALL THREE device-side CTR
    ops on its path (rank_attention, the slot_fc batch_fc tower, the
    cross_norm hadamard block) over the sparse PS pull→train→push
    loop. Emits one row per implementation:

        adsrank_pv_examples_per_sec_per_chip           (XLA, default)
        adsrank_pv_examples_per_sec_per_chip_pallas    (fused kernels)

    keyed separately so perf_gate compares each impl against its OWN
    history (interpret-mode CPU rows key apart from real-TPU rows the
    same way the kernel.* microbench rows do — via recorded rounds).
    BENCH_PV_IMPLS=xla|pallas|both selects; sizes scale down off-TPU."""
    import jax
    import jax.numpy as jnp
    import optax

    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.data import DataFeedDesc, SlotDef
    from paddlebox_tpu.data.pv import PvBatchBuilder
    from paddlebox_tpu.models import AdsRank
    from paddlebox_tpu.ops import (fused_seqpool_cvm,
                                   init_cross_norm_summary)
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig

    on_tpu = jax.default_backend() == "tpu"
    n_pvs = int(os.environ.get("BENCH_PV_PVS",
                               "8192" if on_tpu else "512"))
    bs = int(os.environ.get("BENCH_BATCH_SIZE",
                            "4096" if on_tpu else "256"))
    s = int(os.environ.get("BENCH_PV_SLOTS", "8"))
    d_model = int(os.environ.get("BENCH_PV_DMODEL",
                                 "128" if on_tpu else "32"))
    max_rank = 3
    mf_dim = int(os.environ.get("BENCH_MF_DIM", 8))
    dense_dim = 4
    vocab = int(os.environ.get("BENCH_VOCAB", 10_000))
    impls = os.environ.get("BENCH_PV_IMPLS", "both")
    if impls not in ("xla", "pallas", "both"):
        # a typo'd knob must not produce a silent empty round
        raise SystemExit(
            f"BENCH_PV_IMPLS={impls!r}: must be xla, pallas or both")

    slots = [SlotDef("label", "float", 1),
             SlotDef("dense", "float", dense_dim)]
    slots += [SlotDef(f"C{i}", "uint64") for i in range(s)]
    desc = DataFeedDesc(slots=slots, batch_size=bs, label_slot="label",
                        pv_batch_size=max(1, bs // 8),
                        key_bucket_min=max(512, bs * s))
    recs = build_pv_records(n_pvs, s, vocab, dense_dim)
    pvb = PvBatchBuilder(desc, max_rank=max_rank)
    batches = pvb.batches(recs)
    instances = len(recs)
    d = 3 + mf_dim
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3)
    model = AdsRank(d_model=d_model, max_rank=max_rank,
                    hidden=(128, 64), slot_fc=True, cross_norm=True)
    summary = init_cross_norm_summary(1, d_model)

    rows = []
    flag_sets = {"xla": dict(use_pallas_rank_attention=False,
                             use_pallas_batch_fc=False,
                             use_pallas_cross_norm=False),
                 "pallas": dict(use_pallas_rank_attention=True,
                                use_pallas_batch_fc=True,
                                use_pallas_cross_norm=True)}
    for impl in ("xla", "pallas"):
        if impls not in ("both", impl):
            continue
        table = EmbeddingTable(mf_dim=mf_dim, capacity=1 << 20, cfg=cfg,
                               unique_bucket_min=512)
        tx = optax.adam(5e-3)
        b0, ro0 = batches[0]
        with flags_scope(**flag_sets[impl]):
            params = model.init(jax.random.PRNGKey(0),
                                jnp.zeros((bs, s, d)),
                                jnp.zeros((bs, dense_dim)),
                                jnp.asarray(ro0), summary)
            opt = tx.init(params)

            @jax.jit
            def step(params, opt, values_k, segments, show_clk, dense,
                     label, ro, ins_w):
                def loss_fn(params, values_k):
                    pooled = fused_seqpool_cvm(values_k, segments,
                                               show_clk, bs, s)
                    logits = model.apply(params, pooled, dense, ro,
                                         summary)
                    ls = optax.sigmoid_binary_cross_entropy(logits, label)
                    return (jnp.sum(ls * ins_w)
                            / jnp.maximum(ins_w.sum(), 1.0))
                loss, (gp, gk) = jax.value_and_grad(
                    loss_fn, argnums=(0, 1))(params, values_k)
                upd, opt = tx.update(gp, opt, params)
                params = optax.apply_updates(params, upd)
                return params, opt, loss, gk

            def run_epoch(params, opt):
                for batch, ro in batches:
                    idx = table.prepare(batch)
                    values_k = table.pull(idx)
                    show_clk = jnp.stack([jnp.asarray(batch.show),
                                          jnp.asarray(batch.clk)], axis=1)
                    ins_w = jnp.asarray(
                        (batch.show > 0).astype(np.float32))
                    params, opt, loss, gk = step(
                        params, opt, values_k,
                        jnp.asarray(batch.segments), show_clk,
                        jnp.asarray(batch.dense),
                        jnp.asarray(batch.label), jnp.asarray(ro), ins_w)
                    gk = jnp.concatenate(
                        [gk[:, :2], gk[:, 2:] * (-1.0 * bs)], axis=1)
                    table.push(idx, gk)
                    jax.block_until_ready(loss)
                return params, opt

            params, opt = run_epoch(params, opt)     # warmup/compile
            t0 = time.perf_counter()
            for _ in range(num_passes):
                params, opt = run_epoch(params, opt)
            wall = time.perf_counter() - t0
        value = instances * num_passes / max(wall, 1e-9)
        metric = "adsrank_pv_examples_per_sec_per_chip"
        if impl == "pallas":
            metric += "_pallas"
        rows.append({
            "metric": metric, "value": round(value, 1),
            "unit": "examples/sec/chip",
            "vs_baseline": round(value / (1_000_000 / 16), 4),
            "mode": "pv", "shape": "pv", "impl": impl,
            "batch_size": bs, "pv_batch_size": desc.pv_batch_size,
            "instances_per_pass": instances, "n_pvs": n_pvs,
            "num_slots": s, "d_model": d_model, "max_rank": max_rank,
            "passes": num_passes, "wall_sec": round(wall, 3),
            "backend": jax.default_backend(),
        })
    return rows


def measure_serve(shape: str = "uniform") -> list:
    """BENCH_MODE=serve (ISSUE 15 / ROADMAP item 3): the concurrent-
    serving lane. Trains a small DeepFM, publishes it through the
    artifact layer (``BoxPSHelper.publish_base`` → ``ArtifactStore``),
    adopts it into a snapshot-isolated ``ServingModel`` and then
    sustains batched inference (``predict_many`` micro-batches) over
    the training data, measuring:

        serving.{shape}.qps       queries (micro-batches)/sec — higher
                                  is better, the usual gate rule
        serving.{shape}.p99_ms    per-query p99 latency — gated
                                  LOWER-is-better (perf_gate ``*_ms``)

    The p99 comes from exact client-side timings; the same samples
    also land in the ``pbox_serving_latency_seconds`` histogram (the
    scrapeable p50/p99 lines — which additionally carry the cold-start
    compile sample the headline row excludes, so the two are close but
    not identical). BENCH_SERVE_QUERIES overrides the query count;
    sizes scale down off-TPU."""
    import tempfile

    import jax
    import optax

    from paddlebox_tpu.artifacts import ArtifactStore
    from paddlebox_tpu.data import DataFeedDesc, InMemoryDataset, SlotDef
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.ps.box_helper import BoxPSHelper
    from paddlebox_tpu.serving import ServingModel
    from paddlebox_tpu.train import Trainer

    on_tpu = jax.default_backend() == "tpu"
    (shape_slots, shape_avg, _bs, _recs, shape_vocab,
     shape_dist) = SHAPES[shape]
    bs = int(os.environ.get("BENCH_BATCH_SIZE",
                            "4096" if on_tpu else "512"))
    num_records = int(os.environ.get("BENCH_RECORDS",
                                     str(bs * (32 if on_tpu else 16))))
    n_queries = int(os.environ.get("BENCH_SERVE_QUERIES",
                                   "256" if on_tpu else "96"))
    mf_dim = int(os.environ.get("BENCH_MF_DIM", 8))

    slots = [SlotDef("label", "float", 1), SlotDef("dense", "float", 13)]
    slots += [SlotDef(f"C{i}", "uint64")
              for i in range(1, shape_slots + 1)]
    desc = DataFeedDesc(slots=slots, batch_size=bs, label_slot="label",
                        key_bucket_min=(bs * shape_slots
                                        if shape_avg <= 1.0 else 4096))
    ds = InMemoryDataset(desc)
    ds.records = build_records(num_records, num_slots=shape_slots,
                               vocab_per_slot=shape_vocab, seed=11,
                               avg_keys_per_slot=shape_avg,
                               key_dist=shape_dist)
    ds.columnarize()

    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3)
    table = EmbeddingTable(mf_dim=mf_dim, capacity=1 << 21, cfg=cfg,
                           unique_bucket_min=desc.key_bucket_min)
    tr = Trainer(DeepFM(hidden=(64, 32)), table, desc,
                 tx=optax.adam(1e-3))
    tr.train_pass(ds)
    tr.sync_table()

    workdir = tempfile.mkdtemp(prefix="pbox_serve_bench_")
    store = ArtifactStore(os.path.join(workdir, "registry"))
    helper = BoxPSHelper(table)
    helper.publish_base(store)
    dense = os.path.join(workdir, "m")
    tr.save(dense)

    srv = ServingModel(DeepFM(hidden=(64, 32)), desc, mf_dim=mf_dim,
                       capacity=1 << 21)
    srv.adopt(store)
    srv.load_dense(dense + ".dense.pkl")
    srv.register_health()
    batches = list(ds.batches())

    # warmup: compile the serving forward + fault in the host mirror
    srv.predict(batches[0])
    lat: list = []
    examples = 0
    t0 = time.perf_counter()
    done = 0
    while done < n_queries:
        for batch in batches:
            if done >= n_queries:
                break
            q0 = time.perf_counter()
            pred, ins_w = srv.predict(batch, return_valid=True)
            lat.append(time.perf_counter() - q0)
            examples += int(ins_w.sum())
            done += 1
    wall = time.perf_counter() - t0
    lat.sort()
    p99_ms = lat[int(0.99 * (len(lat) - 1))] * 1e3
    p50_ms = lat[len(lat) // 2] * 1e3
    qps = done / max(wall, 1e-9)

    srv.release()
    if not os.environ.get("BENCH_SERVE_KEEP", ""):
        shutil.rmtree(workdir, ignore_errors=True)
    common = dict(mode="serve", shape=shape, batch=bs, queries=done,
                  backend=jax.default_backend(),
                  examples_per_sec=round(examples / max(wall, 1e-9), 1))
    return [
        {"metric": f"serving.{shape}.qps", "value": round(qps, 2),
         "unit": "queries/sec", "p50_ms": round(p50_ms, 4),
         "p99_ms": round(p99_ms, 4), **common},
        {"metric": f"serving.{shape}.p99_ms",
         "value": round(p99_ms, 4), "unit": "ms/query",
         "qps": round(qps, 2), **common},
    ]


def xplane_device_busy_sec(trace_dir: str) -> float:
    """Parse the jax.profiler XPlane dump: summed UNION of XLA-module
    execution intervals on every /device: plane → measured device busy
    seconds (the round-5 answer to 'device_busy_frac is modeled, not
    measured')."""
    import glob as _glob

    import jax
    paths = _glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                       recursive=True)
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    pd = jax.profiler.ProfileData.from_file(sorted(paths)[-1])
    iv = []
    for plane in pd.planes:
        if not plane.name.startswith("/device:"):
            continue
        for line in plane.lines:
            if line.name != "XLA Modules":
                continue
            for ev in line.events:
                iv.append((float(ev.start_ns),
                           float(ev.start_ns) + float(ev.duration_ns)))
    iv.sort()
    busy = 0.0
    cur_s = cur_e = None
    for s, e in iv:
        if cur_e is None or s > cur_e:
            if cur_e is not None:
                busy += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    if cur_e is not None:
        busy += cur_e - cur_s
    return busy / 1e9


_PERF_GATE_MOD = None


def _perf_gate():
    """scripts/perf_gate loaded by path once (scripts/ is not a
    package; a bench run emits several rows)."""
    global _PERF_GATE_MOD
    if _PERF_GATE_MOD is None:
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "perf_gate", os.path.join(os.path.dirname(os.path.abspath(
                __file__)), "scripts", "perf_gate.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _PERF_GATE_MOD = mod
    return _PERF_GATE_MOD


def emit_result(row: dict) -> None:
    """Print one bench JSON line AND record it on the perf-regression
    trajectory (scripts/perf_gate.py): the recorded best per metric is
    what `perf_gate.py --check` gates future runs against, and a live
    row landing below the gate prints a loud REGRESSION banner here.
    BENCH_TRAJECTORY=0 disables recording; =path overrides."""
    print(json.dumps(row))
    if os.environ.get("BENCH_TRAJECTORY", "") == "0":
        return
    try:
        _perf_gate().record_result(row)
    except Exception as e:  # recording must never eat the bench output
        print(f"perf_gate record failed: {e}", file=sys.stderr)


def setup_telemetry() -> None:
    """Write the run's telemetry JSONL next to the BENCH_*.json artifacts
    (repo root — same dir as this script), so every bench round carries
    per-pass stage/queue/HBM attribution for free
    (scripts/telemetry_report.py renders it). BENCH_TELEMETRY_JSONL
    overrides the path; =0 disables.

    BENCH_TRACE=1 (or =path) additionally records the causal pass
    trace (obs/trace): per-lane Chrome rows — main / preload.worker /
    epilogue.lane / ssd.compact — with build→consume flow arrows,
    saved at exit as BENCH_trace.json. Default OFF: the headline runs
    with tracing inert (the hub.active contract)."""
    import atexit

    from paddlebox_tpu.obs.hub import get_hub
    from paddlebox_tpu.obs.sinks import JsonlSink
    dest = os.environ.get("BENCH_TELEMETRY_JSONL", "")
    here = os.path.dirname(os.path.abspath(__file__))
    if dest != "0":
        path = dest or os.path.join(here, "BENCH_telemetry.jsonl")
        get_hub().add_sink(JsonlSink(path, truncate=True))
        print(f"telemetry jsonl: {path}", file=sys.stderr)
    tdest = os.environ.get("BENCH_TRACE", "")
    if tdest and tdest != "0":
        from paddlebox_tpu.obs.trace import ChromeLaneTraceSink
        from paddlebox_tpu.utils.profiler import ChromeTraceWriter
        tpath = (tdest if tdest != "1"
                 else os.path.join(here, "BENCH_trace.json"))
        writer = ChromeTraceWriter()
        get_hub().add_sink(ChromeLaneTraceSink(writer))
        atexit.register(writer.save, tpath)
        print(f"pass trace: {tpath}", file=sys.stderr)


def main() -> None:
    import optax
    from paddlebox_tpu.config import FLAGS
    from paddlebox_tpu.data import DataFeedDesc, InMemoryDataset, SlotDef
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.train import PassPreloader, Trainer

    setup_telemetry()

    # workload shape (BASELINE.json ladder): "uniform" = 26 slots, one
    # key each (rung 2 steady state); "ragged" = 26 slots, avg 5
    # variable keys/slot (the feed-log shape, data_feed.h:2066-2287);
    # "thousand" = 1000+ sparse slots, one key each (rung 4)
    shape = os.environ.get("BENCH_SHAPE", "uniform")
    # per-slot vocab: thousand-slot workloads share the key budget (1000
    # slots x 100k would overflow the 2^23-row table)
    (shape_slots, shape_avg, bs_default, rec_default,
     shape_vocab, shape_dist) = SHAPES[shape]
    bs = int(os.environ.get("BENCH_BATCH_SIZE", bs_default))
    num_records = int(os.environ.get("BENCH_RECORDS", rec_default))
    mf_dim = int(os.environ.get("BENCH_MF_DIM", 8))
    num_passes = int(os.environ.get("BENCH_PASSES", 5))
    mode = os.environ.get("BENCH_MODE", "resident")
    if mode == "multichip":
        # subprocess-per-chip-count scaling bench (ISSUE 11) — the
        # parent never touches jax itself
        measure_multichip(shape=shape)
        return
    if mode == "pv":
        # PV-batch rank-attention lane (ISSUE 13): proves the CTR op
        # family in a real pull→train→push loop, one row per impl
        for row in measure_pv(int(os.environ.get("BENCH_PASSES", 3))):
            emit_result(row)
        return
    if mode == "serve":
        # concurrent-serving lane (ISSUE 15): snapshot-isolated
        # batched inference qps + p99 latency (p99 gates lower-is-
        # better — scripts/perf_gate.py *_ms rule)
        for row in measure_serve(shape):
            emit_result(row)
        return
    FLAGS.log_period_steps = 10 ** 9
    # the exact f64 host AUC finalize pulls the [2, 1e6] bucket tables
    # over the tunnel per pass; the bench opts into the device reduce
    # (documented tunnel optimization, ~1e-5 f32 drift)
    FLAGS.auc_device_reduce = True

    slots = [SlotDef("label", "float", 1), SlotDef("dense", "float", 13)]
    slots += [SlotDef(f"C{i}", "uint64") for i in range(1, shape_slots + 1)]
    # uniform: one key per slot → exact key bucket (bs*S), zero padding
    # waste and a single compile variant; ragged: bucket rides the max
    desc = DataFeedDesc(slots=slots, batch_size=bs, label_slot="label",
                        key_bucket_min=(bs * shape_slots
                                        if shape_avg <= 1.0 else 4096))

    def make_ds(seed: int) -> InMemoryDataset:
        d = InMemoryDataset(desc)
        d.records = build_records(num_records, num_slots=shape_slots,
                                  vocab_per_slot=shape_vocab, seed=seed,
                                  avg_keys_per_slot=shape_avg,
                                  key_dist=shape_dist)
        d.columnarize()
        return d

    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3)
    metric = "deepfm_ctr_examples_per_sec_per_chip"
    if shape != "uniform":
        metric += f"_{shape}"
    chips = 1

    if mode == "sharded":
        # mesh-mode benchmark: the SHARDED trainer (key%N all_to_all
        # embedding routing + psum dense + sharded AUC) over a mesh of
        # every visible device — 1 real chip here, or a virtual CPU mesh
        # under JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_
        # device_count=N. Reported value stays PER-CHIP for a comparable
        # vs_baseline.
        import jax
        from paddlebox_tpu.parallel import make_mesh
        from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
        from paddlebox_tpu.train.sharded import ShardedTrainer
        chips = len(jax.devices())
        metric += "_sharded"
        mesh = make_mesh(chips)
        table = ShardedEmbeddingTable(
            chips, mf_dim=mf_dim, capacity_per_shard=(1 << 23) // chips,
            cfg=cfg, req_bucket_min=1 << 12, serve_bucket_min=1 << 12)
        swire = os.environ.get("BENCH_FLOAT_WIRE", "q8")
        if swire not in ("q8", "f32"):
            print(f"warning: BENCH_FLOAT_WIRE={swire} unsupported in "
                  "sharded mode, using f32", file=sys.stderr)
            swire = "f32"
        tr = ShardedTrainer(DeepFM(hidden=(512, 256, 128)), table,
                            desc, mesh, tx=optax.adam(1e-3),
                            float_wire=swire)
        build_fn = tr.build_resident_pass
        if "BENCH_ARENA" in os.environ:
            print("warning: BENCH_ARENA is ignored in sharded mode",
                  file=sys.stderr)
    elif mode in ("tiered", "stream"):
        pass  # table/trainer built inside the mode's measurement branch
    else:
        # slot-arena allocation → the resident path ships the COMPACT
        # wire (per-key ~17-bit slot-local rows, no dedup streams); set
        # BENCH_ARENA=0 to measure the host-dedup wire instead
        arena = int(os.environ.get("BENCH_ARENA", "1"))
        table = EmbeddingTable(mf_dim=mf_dim, capacity=1 << 23, cfg=cfg,
                               unique_bucket_min=1 << 12,
                               arena_slots=shape_slots if arena else None)
        tr = Trainer(DeepFM(hidden=(512, 256, 128)), table, desc,
                     tx=optax.adam(1e-3), prefetch=8)
        build_fn = None

    extras = {"mode": mode, "shape": shape, "batch_size": bs,
              "records_per_pass": num_records, "num_slots": shape_slots,
              "avg_keys_per_slot": shape_avg}
    if mode == "tiered":
        emit_result(measure_tiered(
            int(os.environ.get("BENCH_PASSES", 4)), shape=shape))
        return
    elif mode == "stream":
        # windowed streaming-ingest bench (docs/RESILIENCE.md
        # §Streaming): criteo-format text files through the windowed
        # QueueDataset + Trainer.train_stream — end-to-end ingest
        # (parse, window dispatch, train, stream-boundary checkpoints),
        # headline in windows/sec. The first window is the warmup
        # (compile + first upload); the measured call CONTINUES the same
        # stream in-process, which is exactly the resumable-window
        # contract the mode exists to exercise.
        import shutil
        import tempfile
        from paddlebox_tpu.data import DatasetFactory
        from paddlebox_tpu.data.criteo import generate_criteo_files
        from paddlebox_tpu.train.checkpoint import CheckpointManager
        n_files = int(os.environ.get("BENCH_STREAM_FILES", "12"))
        rows = int(os.environ.get("BENCH_STREAM_ROWS_PER_FILE", "2048"))
        FLAGS.stream_window_files = int(
            os.environ.get("BENCH_STREAM_WINDOW_FILES", "2"))
        FLAGS.stream_ckpt_every_windows = int(
            os.environ.get("BENCH_STREAM_CKPT_EVERY", "2"))
        sdesc = DataFeedDesc.criteo(batch_size=bs)
        sdesc.key_bucket_min = max(4096, bs * 26)
        stream_tr = Trainer(
            DeepFM(hidden=(512, 256, 128)),
            EmbeddingTable(mf_dim=mf_dim, capacity=1 << 23, cfg=cfg,
                           unique_bucket_min=1 << 12),
            sdesc, tx=optax.adam(1e-3))
        base = tempfile.mkdtemp(prefix="pbox_stream_bench_")
        try:
            files = generate_criteo_files(
                os.path.join(base, "data"), num_files=n_files,
                rows_per_file=rows, vocab_per_slot=100_000,
                seed=FLAGS.seed)
            ds = DatasetFactory().create_dataset("QueueDataset", sdesc)
            ds.set_filelist(files)
            cm = CheckpointManager(os.path.join(base, "ckpt"))
            stream_tr.train_stream(ds, cm, max_windows=1)  # warmup
            t0 = time.perf_counter()
            out = stream_tr.train_stream(ds, cm)
            wall = time.perf_counter() - t0
        finally:
            shutil.rmtree(base, ignore_errors=True)
        meas_files = int(out["files"])
        emit_result({
            "metric": "stream_windows_per_sec",
            "value": round(out["windows"] / wall, 3),
            "unit": "windows/sec",
            "vs_baseline": None,
            "mode": mode,
            "window_files": FLAGS.stream_window_files,
            "ckpt_every_windows": FLAGS.stream_ckpt_every_windows,
            "windows": int(out["windows"]),
            "files": meas_files,
            "rows_per_file": rows,
            "batches": int(out["batches"]),
            "replayed_files": int(out["replayed_files"]),
            "files_per_sec": round(meas_files / wall, 2),
            "examples_per_sec": round(meas_files * rows / wall, 1),
            "wall_sec": round(wall, 3),
        })
        return
    elif mode == "streaming":
        # distinct gate key: the per-batch streaming pass measures a
        # different pipeline than the resident headline, and the perf
        # trajectory (scripts/perf_gate.py) keys on the metric name —
        # sharing the resident name would gate streaming runs against
        # the resident recorded best
        metric += "_streaming"
        ds = make_ds(0)
        warm = InMemoryDataset(desc)
        warm.records = build_records(bs * 3, num_slots=shape_slots,
                                     vocab_per_slot=shape_vocab, seed=99,
                                     avg_keys_per_slot=shape_avg,
                                     key_dist=shape_dist)
        warm.columnarize()
        tr.train_pass(warm)
        res = tr.train_pass(ds)
        value = res["examples_per_sec"]
    else:
        # Device-resident passes with double-buffered preload — the
        # reference's steady state (preload_into_memory overlaps training,
        # BeginPass stages the pass in HBM; SURVEY.md §3.3). Pass 0 pays
        # compile+upload; measurement is ADAPTIVE: at least BENCH_PASSES
        # passes, extended until the trimmed estimate stabilizes within
        # 10% (a bimodal tunnel cannot fake a steady rate) or a
        # pass/wall budget is hit. Datasets come from a cycled pool:
        # synthetic data GENERATION is the data source, not the system
        # under test (the measured pipeline still includes batch build,
        # row assign and upload via the preloader).
        import itertools
        pool = [make_ds(s) for s in range(4)]
        datasets = itertools.cycle(pool)
        # q8 float wire (per-column affine int8 dense + exact-u8
        # label/show/clk) — the H2D wire is the measured bottleneck on
        # tunneled runtimes and CTR dense features fit 8-bit affine
        # (test_resident_q8_wire_learns covers AUC parity)
        import jax.numpy as jnp
        wire = os.environ.get("BENCH_FLOAT_WIRE", "q8")
        wire = {"bf16": jnp.bfloat16, "f32": np.float32}.get(wire, wire)
        blockp = os.environ.get("BENCH_BLOCK_PRELOAD", "0") == "1"
        debug = os.environ.get("BENCH_DEBUG", "0") == "1"
        no_overlap = os.environ.get("BENCH_NO_OVERLAP", "0") == "1"
        # pipeline depth: FLAGS.preload_depth unless overridden;
        # BENCH_NO_OVERLAP = the manual kick-per-pass control (depth 0)
        depth = (0 if no_overlap else
                 int(os.environ.get("BENCH_PRELOAD_DEPTH",
                                    str(FLAGS.preload_depth))))
        pre = (PassPreloader(datasets, build_fn=build_fn, depth=depth)
               if build_fn is not None else
               PassPreloader(datasets, table, floats_dtype=wire,
                             block_transfers=blockp, depth=depth))
        pre.start_next()
        rp = pre.wait()
        pre.start_next()
        tr.train_pass_resident(rp)          # warmup/compile pass
        # per-pass wall includes that pass's preload wait
        walls_l, waits_l, trains_l, rates_l, wire_l = [], [], [], [], []
        max_passes = int(os.environ.get("BENCH_MAX_PASSES",
                                        str(max(12, num_passes))))
        budget_s = float(os.environ.get("BENCH_WALL_BUDGET_SEC", "180"))

        def trimmed_kept(walls):
            """Indices of the kept passes after dropping the worst ~20%
            (≥1, but never the only pass): one-off tunnel stalls are
            environment noise; the TOTAL-based rate over the kept passes
            resists the alternating-wall pattern a plain median
            overstates."""
            d = max(1, len(walls) // 5) if len(walls) > 1 else 0
            order = np.argsort(walls)
            return order[:len(walls) - d], d

        def trimmed_estimate(walls):
            kept, d = trimmed_kept(walls)
            return (num_records * len(kept)
                    / sum(walls[i] for i in kept) / chips), d

        est_hist = []
        stable = False
        bench_t0 = time.perf_counter()
        while True:
            t0 = time.perf_counter()
            rp = pre.wait()
            t_wait = time.perf_counter() - t0
            if not no_overlap:
                pre.start_next()
            t1 = time.perf_counter()
            tr.train_pass_resident(rp)
            t_train = time.perf_counter() - t1
            if no_overlap:
                pre.start_next()
            wall = time.perf_counter() - t0
            if debug:
                print(f"pass: wait={t_wait:.3f}s train={t_train:.3f}s",
                      file=sys.stderr)
            walls_l.append(wall)
            waits_l.append(t_wait)
            trains_l.append(t_train)
            rates_l.append(rp.num_records / wall)
            if hasattr(rp, "nbytes"):
                wire_l.append(rp.nbytes())
            if len(walls_l) >= 2:
                est_hist.append(trimmed_estimate(walls_l)[0])
            if len(walls_l) < num_passes:
                continue
            # stable = two consecutive estimate moves both within 10%
            stable = (len(est_hist) >= 3
                      and abs(est_hist[-1] - est_hist[-2])
                      <= 0.10 * est_hist[-2]
                      and abs(est_hist[-2] - est_hist[-3])
                      <= 0.10 * est_hist[-3])
            if stable or len(walls_l) >= max_passes \
                    or time.perf_counter() - bench_t0 > budget_s:
                break
        # one EXTRA traced pass (not in the headline estimate): XPlane
        # device-span measurement of the TRUE duty cycle — the modeled
        # device_busy_frac below divides a wire-free rerun rate into
        # wall and inherits that rerun's error; this one is measured
        # (VERDICT r4 item 8)
        import jax
        busy_meas = None
        if os.environ.get("BENCH_XPLANE", "1") == "1":
            import shutil
            import tempfile
            xdir = tempfile.mkdtemp(prefix="pbox_xplane_")
            try:
                rp = pre.wait()
                pre.start_next()
                t0 = time.perf_counter()
                with jax.profiler.trace(xdir):
                    tr.train_pass_resident(rp)
                wall_t = time.perf_counter() - t0
                busy_meas = xplane_device_busy_sec(xdir) / wall_t
            except Exception as e:
                print(f"xplane duty measurement failed: {e}",
                      file=sys.stderr)
            finally:
                shutil.rmtree(xdir, ignore_errors=True)
        # quiesce the pipeline before the wire-free rerun: the cycled
        # dataset source ALWAYS has passes building ahead, and their
        # background batch-build + H2D upload would contaminate
        # dev_only (deflating device_only_ex_per_sec /
        # device_busy_frac). stop() halts the worker (an in-flight
        # build aborts or completes), drain() joins it, and the
        # remaining staged passes' transfers are waited out.
        pre.drain()
        while True:
            rp_next = pre.wait()
            if rp_next is None:
                break
            if getattr(rp_next, "dev", None) is not None:
                jax.block_until_ready(jax.tree.leaves(rp_next.dev))
        # device-only rate: re-run the LAST staged pass (its wire is
        # already resident, so nothing rides the tunnel) — the clean
        # numerator for MFU / duty-cycle attribution. TWO reruns, the
        # second measured: a single rerun underreads steady state ~15%
        # (first-rerun warmup effects — XPlane-verified on the sharded
        # pass, DESIGN_NOTES §4i addendum). NOTE: these are real
        # training passes (params/table/AUC see the last pass again);
        # they run after every measured number is taken and the bench
        # reports throughput only, so nothing downstream reads the
        # perturbed model state — keep them LAST if extending the bench.
        tr.train_pass_resident(rp)
        t0 = time.perf_counter()
        tr.train_pass_resident(rp)
        dev_only = rp.num_records / (time.perf_counter() - t0)
        value, n_dropped = trimmed_estimate(walls_l)
        # evidence block: per-pass arrays + duty cycle + wire + MFU
        # (PrintSyncTimer per-stage reporting, box_wrapper.cc:1182)
        params = (tr.state.params if hasattr(tr.state, "params")
                  else None)
        fpe = dense_flops_per_example(params) if params is not None else 0
        peak = float(os.environ.get("BENCH_PEAK_TFLOPS", "459")) * 1e12
        # honest duty cycle: the device's ACTUAL compute time per pass is
        # records/dev_only (wire-free rerun); jnp.asarray is lazy, so
        # sum(train)/sum(wall) counts in-step H2D waits as "busy" and
        # saturates exactly when the device is idlest (the round-3
        # reviewer finding) — report both, clearly named
        n_meas = len(walls_l)
        dev_time_total = num_records * n_meas / max(dev_only, 1e-9)
        extras.update(
            passes=n_meas,
            passes_dropped=n_dropped,
            estimate_stable=stable,
            # deep pass pipeline attribution (ISSUE 5 / BENCH_r06):
            # depth, total prologue stall over the measured passes, and
            # the per-stage build-seconds breakdown so a starved
            # pipeline names its slow stage (front/dedup/pack/h2d)
            preload_depth=pre.depth if not no_overlap else 0,
            preload_depth_clamped=pre.depth_clamped,
            prologue_wait_sec_total=round(sum(waits_l), 4),
            preload_builds=pre.builds,
            preload_build_sec_total=round(pre.build_sec_total, 4),
            preload_build_stage_sec={
                k: round(v, 4)
                for k, v in sorted(pre.build_stage_sec.items())},
            per_pass_wall_sec=[round(w, 3) for w in walls_l],
            per_pass_wait_sec=[round(w, 3) for w in waits_l],
            per_pass_train_sec=[round(w, 3) for w in trains_l],
            per_pass_ex_per_sec=[round(r, 1) for r in rates_l],
            # fraction of wall the device spent on real compute
            device_busy_frac=round(
                min(dev_time_total / max(sum(walls_l), 1e-9), 1.0), 4),
            # XPlane-measured duty over one traced (extra) pass: union
            # of XLA-module device spans / pass wall — measured, not
            # derived from the wire-free rerun model
            device_busy_frac_measured=(None if busy_meas is None
                                       else round(busy_meas, 4)),
            # fraction of wall spent inside the step CALL (includes
            # waiting on in-flight wire — NOT device busyness)
            wall_in_step_frac=round(sum(trains_l) / max(sum(walls_l),
                                                        1e-9), 4),
            flops_per_example_dense=round(fpe),
            # per-chip rate over one chip's peak (value is already /chips)
            mfu_dense=round(value * fpe / peak, 6),
            # wire-free rerun of the staged pass: pure device throughput
            device_only_ex_per_sec=round(dev_only / chips, 1),
            mfu_dense_device_only=round(dev_only / chips * fpe / peak, 6),
            peak_tflops_assumed=peak / 1e12,
        )
        if wire_l:
            wire_rate = sum(wire_l) / 1e6 / max(sum(walls_l), 1e-9)
            # the normalized rate uses the SAME kept-pass set as the
            # trimmed headline — mixing a trimmed numerator with an
            # untrimmed wire rate would inflate with stall count
            kept, _ = trimmed_kept(walls_l)
            kept_wire_rate = (sum(wire_l[i] for i in kept) / 1e6
                              / max(sum(walls_l[i] for i in kept), 1e-9))
            extras.update(
                wire_mb_per_pass=round(np.mean(wire_l) / 1e6, 2),
                wire_bytes_per_record=round(
                    np.mean(wire_l) / num_records, 1),
                wire_mb_per_sec=round(wire_rate, 2),
                # FIRST-CLASS wire-normalized rate: ex/s per wire-MB/s is
                # invariant to tunnel weather (code speed per unit of
                # wire the box actually moved) — the reproducible
                # companion when the raw headline rides a shared tunnel
                ex_per_sec_per_wire_mb_per_sec=round(
                    value / max(kept_wire_rate, 1e-9), 1))
        if (mode == "sharded"
                and os.environ.get("BENCH_A2A_PROBE", "1") == "1"):
            # measured exchange/compute attribution (ISSUE 11;
            # train/a2a_probe): per-chunk a2a vs pool seconds, plus the
            # fused-schedule A/B over the same wire. Runs AFTER every
            # headline number (its timed steps are real training steps,
            # same discipline as the wire-free rerun); emits
            # a2a.pull.*/a2a.push spans when BENCH_TRACE is on, and
            # exchange_wait rides the next pass event's critical_path.
            try:
                from paddlebox_tpu.train.a2a_probe import probe_exchange
                pr = probe_exchange(tr, dataset=pool[0])
                # one extra wire-free pass so the probe's exchange_wait
                # part rides a pass event's critical_path block (the
                # telemetry/report view of the attribution)
                tr.train_pass_resident(rp)
                extras.update(
                    a2a_chunks=pr["a2a_chunks"],
                    exchange_overlap_frac=pr["exchange_overlap_frac"],
                    exchange_sec_total=pr["exchange_sec_total"],
                    exchange_wait_sec=pr["exchange_wait_sec"],
                    a2a_pull_sec=pr["a2a_pull_sec"],
                    a2a_pool_sec=pr["pool_sec"],
                    a2a_push_sec=pr["push_sec"],
                    step_monolithic_sec=pr["step_monolithic_sec"],
                    step_chunked_sec=pr["step_chunked_sec"])
            except Exception as e:  # probe must never eat the headline
                print(f"a2a probe failed: {e}", file=sys.stderr)
    baseline_per_chip = 1_000_000 / 16  # v5p-32 north-star / chips
    if (mode == "resident" and shape == "uniform"
            and os.environ.get("BENCH_TIERED_ROW", "1") == "1"):
        # the driver runs plain `python bench.py`: emit the tiered
        # delta-staging architecture row in the same artifact (VERDICT
        # r4 item 5 — PrintSyncTimer per-stage logs are emitted
        # unconditionally, box_wrapper.cc:1182). Headline line stays
        # LAST for parsers that take the final line.
        try:
            emit_result(measure_tiered(num_passes=3))
        except Exception as e:  # the headline must survive a tiered trip
            print(f"tiered row failed: {e}", file=sys.stderr)
    if mode == "resident" and "ex_per_sec_per_wire_mb_per_sec" in extras:
        # the tunnel-invariant companion metric as its own line:
        # raw ex/s swings 2-3x with shared-tunnel weather while this
        # reproduces to the decimal (docs/BENCH_SHAPES.md round 4);
        # vs_baseline is against the round-4 recorded value so
        # round-over-round comparisons stop riding tunnel weather
        r04_ref = {"uniform": 14032.1, "ragged": 2257.2,
                   "thousand": 495.8}.get(shape)
        emit_result({
            "metric": metric + "_per_wire_mb_per_sec",
            "value": extras["ex_per_sec_per_wire_mb_per_sec"],
            "unit": "examples/sec per wire-MB/s",
            "vs_baseline": (round(
                extras["ex_per_sec_per_wire_mb_per_sec"] / r04_ref, 4)
                if r04_ref else None),
            "baseline_ref": "round-4 recorded value (BENCH_SHAPES.md)",
        })
    emit_result({
        "metric": metric,
        "value": round(value, 1),
        "unit": "examples/sec/chip",
        "vs_baseline": round(value / baseline_per_chip, 4),
        **extras,
    })


if __name__ == "__main__":
    main()
