#!/usr/bin/env python
"""Benchmark: DeepFM CTR training throughput on one chip.

Prints one JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline derivation (BASELINE.md): north-star is 1M examples/sec on a
v5p-32 slice (16 chips) ⇒ 62,500 examples/sec/chip. vs_baseline is
measured chip throughput / 62,500.

The measured pass mirrors the reference's steady state (SURVEY.md §3.2):
data already resident in memory (loaded during the previous pass window),
per-batch host prep (dedup + row assign) overlapped with device compute via
the prefetch thread, one fused jit step per batch.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def build_records(num_records: int, num_slots: int = 26,
                  vocab_per_slot: int = 100_000, seed: int = 0):
    """Synthetic criteo-shaped records, built columnar-fast."""
    from paddlebox_tpu.data.record import SlotRecord
    rng = np.random.default_rng(seed)
    keys_all = rng.integers(0, vocab_per_slot, size=(num_records, num_slots))
    keys_all = (keys_all + np.arange(num_slots) * vocab_per_slot).astype(np.uint64)
    dense_all = rng.normal(size=(num_records, 13)).astype(np.float32)
    labels = (rng.random(num_records) < 0.25).astype(np.float32)
    offsets = np.arange(num_slots + 1, dtype=np.int32)
    recs = [
        SlotRecord(keys=keys_all[i], slot_offsets=offsets,
                   dense=dense_all[i], label=float(labels[i]), show=1.0,
                   clk=float(labels[i]))
        for i in range(num_records)
    ]
    return recs


def main() -> None:
    import optax
    from paddlebox_tpu.config import FLAGS
    from paddlebox_tpu.data import DataFeedDesc, InMemoryDataset, SlotDef
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.train import PassPreloader, Trainer

    bs = int(os.environ.get("BENCH_BATCH_SIZE", 8192))
    num_records = int(os.environ.get("BENCH_RECORDS", 262_144))
    mf_dim = int(os.environ.get("BENCH_MF_DIM", 8))
    num_passes = int(os.environ.get("BENCH_PASSES", 3))
    mode = os.environ.get("BENCH_MODE", "resident")
    FLAGS.log_period_steps = 10 ** 9

    slots = [SlotDef("label", "float", 1), SlotDef("dense", "float", 13)]
    slots += [SlotDef(f"C{i}", "uint64") for i in range(1, 27)]
    # one key per slot → exact key bucket (bs*26): zero padding waste and
    # a single compile variant
    desc = DataFeedDesc(slots=slots, batch_size=bs, label_slot="label",
                        key_bucket_min=bs * 26)

    def make_ds(seed: int) -> InMemoryDataset:
        d = InMemoryDataset(desc)
        d.records = build_records(num_records, seed=seed)
        d.columnarize()
        return d

    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3)
    metric = "deepfm_ctr_examples_per_sec_per_chip"
    chips = 1

    if mode == "sharded":
        # mesh-mode benchmark: the SHARDED trainer (key%N all_to_all
        # embedding routing + psum dense + sharded AUC) over a mesh of
        # every visible device — 1 real chip here, or a virtual CPU mesh
        # under JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_
        # device_count=N. Reported value stays PER-CHIP for a comparable
        # vs_baseline.
        import jax
        from paddlebox_tpu.parallel import make_mesh
        from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
        from paddlebox_tpu.train.sharded import ShardedTrainer
        chips = len(jax.devices())
        metric += "_sharded"
        mesh = make_mesh(chips)
        table = ShardedEmbeddingTable(
            chips, mf_dim=mf_dim, capacity_per_shard=(1 << 23) // chips,
            cfg=cfg, req_bucket_min=1 << 12, serve_bucket_min=1 << 12)
        swire = os.environ.get("BENCH_FLOAT_WIRE", "q8")
        if swire not in ("q8", "f32"):
            print(f"warning: BENCH_FLOAT_WIRE={swire} unsupported in "
                  "sharded mode, using f32", file=sys.stderr)
            swire = "f32"
        tr = ShardedTrainer(DeepFM(hidden=(512, 256, 128)), table,
                            desc, mesh, tx=optax.adam(1e-3),
                            float_wire=swire)
        build_fn = tr.build_resident_pass
        if "BENCH_ARENA" in os.environ:
            print("warning: BENCH_ARENA is ignored in sharded mode",
                  file=sys.stderr)
    else:
        # slot-arena allocation → the resident path ships the COMPACT
        # wire (per-key ~17-bit slot-local rows, no dedup streams); set
        # BENCH_ARENA=0 to measure the host-dedup wire instead
        arena = int(os.environ.get("BENCH_ARENA", "1"))
        table = EmbeddingTable(mf_dim=mf_dim, capacity=1 << 23, cfg=cfg,
                               unique_bucket_min=1 << 12,
                               arena_slots=26 if arena else None)
        tr = Trainer(DeepFM(hidden=(512, 256, 128)), table, desc,
                     tx=optax.adam(1e-3), prefetch=8)
        build_fn = None

    if mode == "streaming":
        ds = make_ds(0)
        warm = InMemoryDataset(desc)
        warm.records = build_records(bs * 3, seed=99)
        warm.columnarize()
        tr.train_pass(warm)
        res = tr.train_pass(ds)
        value = res["examples_per_sec"]
    else:
        # Device-resident passes with double-buffered preload — the
        # reference's steady state (preload_into_memory overlaps training,
        # BeginPass stages the pass in HBM; SURVEY.md §3.3). Pass 0 pays
        # compile+upload; measurement covers passes 1..num_passes wall
        # clock, preloads overlapped. Datasets are materialized up front:
        # synthetic data GENERATION is the data source, not the system
        # under test (the measured pipeline still includes batch build,
        # row assign and upload via the preloader).
        datasets = iter([make_ds(s) for s in range(num_passes + 1)])
        # q8 float wire (per-column affine int8 dense + exact-u8
        # label/show/clk) — the H2D wire is the measured bottleneck on
        # tunneled runtimes and CTR dense features fit 8-bit affine
        # (test_resident_q8_wire_learns covers AUC parity)
        import jax.numpy as jnp
        wire = os.environ.get("BENCH_FLOAT_WIRE", "q8")
        wire = {"bf16": jnp.bfloat16, "f32": np.float32}.get(wire, wire)
        blockp = os.environ.get("BENCH_BLOCK_PRELOAD", "0") == "1"
        pre = (PassPreloader(datasets, build_fn=build_fn)
               if build_fn is not None else
               PassPreloader(datasets, table, floats_dtype=wire,
                             block_transfers=blockp))
        pre.start_next()
        rp = pre.wait()
        pre.start_next()
        tr.train_pass_resident(rp)          # warmup/compile pass
        # per-pass wall includes that pass's preload wait; the
        # steady-state estimate below drops the single worst pass and
        # uses total records / total remaining wall
        per_pass = []
        debug = os.environ.get("BENCH_DEBUG", "0") == "1"
        no_overlap = os.environ.get("BENCH_NO_OVERLAP", "0") == "1"
        for _ in range(num_passes):
            t0 = time.perf_counter()
            rp = pre.wait()
            t_wait = time.perf_counter() - t0
            if not no_overlap:
                pre.start_next()
            t1 = time.perf_counter()
            tr.train_pass_resident(rp)
            t_train = time.perf_counter() - t1
            if no_overlap:
                pre.start_next()
            if debug:
                print(f"pass: wait={t_wait:.3f}s train={t_train:.3f}s",
                      file=sys.stderr)
            per_pass.append(rp.num_records / (time.perf_counter() - t0))
        # steady-state estimate: drop the single worst pass (one-off
        # tunnel stalls are environment noise), then TOTAL-based rate —
        # a plain median can overstate when pass walls alternate
        walls = sorted(num_records / r for r in per_pass)
        if len(walls) > 1:
            walls = walls[:-1]
        value = num_records * len(walls) / sum(walls) / chips
    baseline_per_chip = 1_000_000 / 16  # v5p-32 north-star / chips
    print(json.dumps({
        "metric": metric,
        "value": round(value, 1),
        "unit": "examples/sec/chip",
        "vs_baseline": round(value / baseline_per_chip, 4),
    }))


if __name__ == "__main__":
    main()
